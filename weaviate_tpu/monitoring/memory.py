"""Memory & capacity observability: the device/host/disk byte ledger.

Every ROADMAP scale item (the mesh promotion, the clustered 10M-100M
layouts, multi-tier quantization) is gated by one resource the
observability plane could not see: **bytes**. An HBM OOM on a chip
session surfaces as an opaque rc=3, and the host side holds several
unaccounted caches (the breaker's fallback rows, the auditor's rows
cache, the shard allowList cache, COW transients). This module is the
capacity twin of the perf window (monitoring/perf.py) and the quality
auditor (monitoring/quality.py): a continuous, always-on accounting of
what the process holds, how fast that grows under ingest, and when it
runs out.

How it works:

- **device ledger**: every index mutation that lands device buffers
  stamps its component byte sizes ANALYTICALLY (shapes x dtypes — zero
  device syncs; the stamped values equal the buffers' ``nbytes``
  exactly) at ``IndexSnapshot`` publish (index/tpu.py) and at every mesh
  slab mutation (index/mesh.py, per-device via ``ndev``). Search
  dispatches never touch the ledger — the hot path is untouched
  (spy-pinned in tests/test_memory_ledger.py);
- **host ledger**: host consumers register pull providers (the breaker's
  ``_host_rows_cache``, the auditor rows cache, ``Shard._allow_cache``,
  the slot_to_doc/host-tombstone mirrors, staged pending rows) that are
  polled on write-path stamps (throttled) and on demand — the SAME
  sizing helpers back ``/debug/index``, so the two surfaces can never
  disagree;
- **write-path lifecycle**: flush/device-write/tombstone/compress/
  compact phase timings with rows and bytes moved, COW copy bytes and
  per-flush transient peaks, staged-generation publish lag, and
  write-shape ``jit_first_seen`` facts;
- **forecast**: an ingest-rate EWMA per scope (device/host/disk) yields
  a time-to-exhaustion estimate against the scope's byte budget
  (``device.memory_stats()['bytes_limit']`` where the backend provides
  it, /proc/meminfo for the host, the data volume for disk), with
  quality-style fire-once degradation alerts at a configurable headroom
  threshold;
- **drift**: where the backend reports allocator stats
  (``device.memory_stats()``), the ledger's analytic total is
  cross-checked against ``bytes_in_use`` — a drift gauge, never trusted
  as primary, and only read at summary time (off every hot path).

Exposure: ``GET /debug/memory`` (same authorizer as pprof/perf/quality),
bounded-cardinality gauges (``weaviate_device_bytes{component}``,
``weaviate_host_bytes{component}``, ``weaviate_disk_bytes{component}``,
``weaviate_memory_headroom_pct{scope}``, ``weaviate_write_flush_ms``,
``weaviate_cow_copy_bytes_total``), and the ``memory`` blocks on
bench.py serving/e2e rows. See docs/memory.md.

Lifecycle mirrors the tracer/perf/quality planes: a process-wide module
global installed by App (``MEMORY_LEDGER_ENABLED``, default on) and
cleared on shutdown; unconfigured (bare-index tests, embedded use) every
stamping entry point is a one-comparison no-op.
"""

from __future__ import annotations

import logging
import shutil
import threading
import time
import weakref
from collections import deque
from typing import Callable, Optional

# one nearest-rank percentile across the monitoring plane: perf/quality/
# memory surfaces must report identical p50/p99 semantics
from weaviate_tpu.monitoring.perf import _pct

_LOG = logging.getLogger(__name__)

# bounded component taxonomies — these tuples ARE the gauge label sets
# (the JGL010 discipline: a foreign component name folds into "other",
# never mints a new series)
DEVICE_COMPONENTS = ("store", "sq_norms", "tombs", "slot_to_doc",
                     "pq_codes", "recon_norms", "rescore_store",
                     "rescore_sq_norms", "allow_words",
                     # the IVF scan plane's slabs (index/tpu.py +
                     # ops/ivf.py): k-means centroids, padded partition
                     # buckets, PCA projection + per-slot low-dim rows
                     "ivf_centroids", "ivf_buckets", "ivf_pca_proj",
                     "ivf_pca_rows",
                     # the 4-bit Quick-ADC ladder (index/tpu.py +
                     # ops/pq4.py): packed two-codes-per-byte slab, its
                     # reconstruction norms, and the shared OPQ rotation
                     "pq4_codes", "pq4_norms", "opq_rot")
HOST_COMPONENTS = ("slot_to_doc", "host_tombs", "host_vecs",
                   "pending_rows", "breaker_rows", "auditor_rows",
                   "allow_cache", "stage_buffers",
                   # the IVF plane's host twins: centroid matrix + PCA
                   # basis (write-path assignment) + per-slot partition
                   # assignment mirror
                   "ivf_host")
DISK_COMPONENTS = ("used", "free", "incident_bundles")
OTHER = "other"
SCOPES = ("device", "host", "disk")

# write-path lifecycle phases (display order in /debug/memory)
WRITE_PHASES = ("flush", "device_write", "apply_tombstones", "compress",
                "compact")

# seconds between degradation log lines per scope (the counter always
# increments once per transition; the log is what gets rate-limited)
ALERT_LOG_INTERVAL_S = 60.0

# min seconds between host-provider / disk refreshes driven by write-path
# stamps (summary() always refreshes)
_REFRESH_MIN_S = 0.5

# per-phase sample cap on top of the time-horizon eviction (perf.py idiom)
_WRITE_SAMPLES_MAX = 8192
# distinct write shapes tracked for jit_first_seen (a runaway shape
# generator must not grow the dict unboundedly)
_SHAPES_MAX = 128


def array_bytes(arr) -> int:
    """Analytic byte size of a (device or host) array: shape x itemsize.
    Never touches device data — the zero-sync contract — and equals the
    array's ``nbytes`` exactly (both are metadata products)."""
    if arr is None:
        return 0
    n = 1
    for s in arr.shape:
        n *= int(s)
    return n * arr.dtype.itemsize


# -- sizing helpers shared with /debug/index ----------------------------------
# These functions are the ONE place cache byte sizes are computed: the
# ledger's host providers call them AND Shard.debug_health()/
# TpuVectorIndex.health() call them, so /debug/memory and /debug/index can
# never disagree on what a cache weighs.


def bitmap_bytes(bm) -> int:
    """HOST byte size of one allowList Bitmap (its sorted-ids array).
    The packed device filter words a hot bitmap may also cache
    (``_words_cache``) are DEVICE bytes and accounted separately —
    see allow_words_device_bytes()."""
    ids = getattr(bm, "_ids", None)
    return int(ids.nbytes) if ids is not None else 0


def allow_words_device_bytes(shard) -> int:
    """DEVICE bytes pinned by the packed filter words cached on the
    bitmaps a shard's allowList cache holds (index _allow_words caches
    one [capacity/32] u32 device array per hot filter). Analytic —
    shape metadata only, zero syncs."""
    try:
        entries = list(getattr(shard, "_allow_cache", {}).values())
    except RuntimeError:
        return 0
    total = 0
    for entry in entries:
        try:
            wc = getattr(entry[1], "_words_cache", None)
            if wc is not None:
                total += array_bytes(wc[1])
        except (TypeError, IndexError, AttributeError):
            pass
    return total


def shard_device_components(shard) -> dict:
    b = allow_words_device_bytes(shard)
    return {"allow_words": b} if b else {}


def allow_cache_bytes(shard) -> int:
    """Total bytes held by a shard's allowList cache (racy snapshot —
    introspection, not an invariant)."""
    try:
        entries = list(getattr(shard, "_allow_cache", {}).values())
    except RuntimeError:  # resized mid-iteration by a concurrent reader
        return 0
    total = 0
    for entry in entries:
        try:
            total += bitmap_bytes(entry[1])
        except (TypeError, IndexError):
            pass
    return total


def host_rows_cache_bytes(vidx) -> int:
    """Bytes pinned by the breaker's host-fallback rows cache (0 when not
    resident). Under PQ the rows tuple may hold a VIEW of host_vecs — the
    view's nbytes still reports what the degraded plane reads; host_vecs
    itself is accounted as its own component."""
    cache = getattr(vidx, "_host_rows_cache", None)
    if cache is None:
        return 0
    try:
        return int(cache[1].nbytes) + int(cache[2].nbytes)
    except (TypeError, IndexError, AttributeError):
        return 0


def auditor_rows_bytes(auditor, vidx=None) -> int:
    """Bytes held by the quality auditor's per-index host-rows cache;
    restricted to one index when ``vidx`` is given (the /debug/index
    per-shard view). Racy snapshot, never takes the auditor's lock."""
    if auditor is None:
        return 0
    try:
        items = list(getattr(auditor, "_rows_cache", {}).items())
    except RuntimeError:
        return 0
    total = 0
    for key, entry in items:
        if vidx is not None and key != id(vidx):
            continue
        try:
            total += int(entry[1].nbytes) + int(entry[2].nbytes)
        except (TypeError, IndexError, AttributeError):
            pass
    return total


def index_host_components(vidx) -> dict:
    """Host-side components of one vector index (single-chip or mesh):
    the slot->doc / tombstone mirrors, the PQ host rows, staged pending
    rows, and the breaker's fallback cache."""
    out: dict = {}
    for name, attr in (("slot_to_doc", "_slot_to_doc"),
                       ("host_tombs", "_host_tombs"),
                       ("host_vecs", "_host_vecs")):
        arr = getattr(vidx, attr, None)
        if arr is not None:
            b = int(arr.nbytes)
            if b:
                out[name] = b
    pending = getattr(vidx, "_pending", None)
    dim = getattr(vidx, "dim", None)
    if pending and dim:
        out["pending_rows"] = len(pending) * int(dim) * 4
    hr = host_rows_cache_bytes(vidx)
    if hr:
        out["breaker_rows"] = hr
    # IVF host twins (index/tpu.py): the centroid matrix + PCA basis the
    # write path assigns against, and the per-slot assignment mirror —
    # tens of MB at scale, and the ledger must see them like every
    # other host mirror
    ivf = 0
    for attr in ("_ivf_centroids_host", "_ivf_pca_host", "_ivf_assign"):
        arr = getattr(vidx, attr, None)
        if arr is not None:
            ivf += int(arr.nbytes)
    if ivf:
        out["ivf_host"] = ivf
    # parked query-staging buffers (the fused-dispatch enqueue pool):
    # racy len-free iteration over a dict-of-lists snapshot — sizes only
    stage = getattr(vidx, "_stage_free", None)
    if stage:
        b = 0
        for bufs in list(stage.values()):
            b += sum(int(x.nbytes) for x in list(bufs))
        if b:
            out["stage_buffers"] = b
    return out


def shard_host_components(shard) -> dict:
    b = allow_cache_bytes(shard)
    return {"allow_cache": b} if b else {}


def auditor_host_components(auditor) -> dict:
    b = auditor_rows_bytes(auditor)
    return {"auditor_rows": b} if b else {}


# -- the provider registries (module-level, ledger-independent) ---------------
# Registration happens at object construction (index/shard/auditor), which
# may precede the ledger's configure (or outlive it across App restarts) —
# so the registries live at module scope and the live ledger reads them.
# Host providers cover host-RAM consumers; device providers cover the few
# DEVICE allocations that live outside snapshot stamping (the packed
# filter words cached on hot allowList bitmaps).

_providers_lock = threading.Lock()
_host_providers: dict = {}    # id(owner) -> (weakref.ref(owner), fn)
_device_providers: dict = {}  # id(owner) -> (weakref.ref(owner), fn)
_disk_providers: dict = {}    # id(owner) -> (weakref.ref(owner), fn)


def _register(registry: dict, owner, fn: Callable) -> None:
    ref = weakref.ref(owner)
    with _providers_lock:
        dead = [k for k, (r, _) in registry.items() if r() is None]
        for k in dead:
            registry.pop(k, None)
        registry[id(owner)] = (ref, fn)


def _poll(registry: dict) -> dict:
    """Poll every live provider -> summed {component: bytes}. Provider
    errors are swallowed (introspection must never break serving)."""
    with _providers_lock:
        items = list(registry.items())
    out: dict = {}
    dead = []
    for key, (ref, fn) in items:
        owner = ref()
        if owner is None:
            dead.append(key)
            continue
        try:
            comps = fn(owner)
        except Exception:  # noqa: BLE001 — a broken provider must not 500
            continue
        for name, b in comps.items():
            if b:
                out[name] = out.get(name, 0) + int(b)
    if dead:
        with _providers_lock:
            for k in dead:
                # re-check under the lock: a recycled id(owner) may have
                # been re-registered by a new live object since we
                # observed the dead weakref (TOCTOU) — never unregister
                # a live provider
                entry = registry.get(k)
                if entry is not None and entry[0]() is None:
                    registry.pop(k, None)
    return out


def register_host_provider(owner, fn: Callable) -> None:
    """Register ``fn(owner) -> {component: bytes}`` as a host-memory
    consumer. The owner is held by weakref only; dead entries prune on
    the next registration or poll."""
    _register(_host_providers, owner, fn)


def register_device_provider(owner, fn: Callable) -> None:
    """Register a DEVICE-memory provider for allocations that live
    outside the snapshot stamping flow (e.g. per-bitmap filter words)."""
    _register(_device_providers, owner, fn)


def register_disk_provider(owner, fn: Callable) -> None:
    """Register a DISK consumer whose bytes should appear as their own
    component beside used/free (the incident flight recorder's bundle
    directory — monitoring/incidents.py). Components are informational
    sub-accounts of ``used``; the scope's budget stays the volume total."""
    _register(_disk_providers, owner, fn)


def host_components() -> dict:
    return _poll(_host_providers)


def device_provider_components() -> dict:
    return _poll(_device_providers)


# -- ingest-rate EWMA ---------------------------------------------------------


class _Rate:
    """EWMA growth rate (bytes/s) of one scope's accounted total. Fed on
    every refresh; negative deltas (compaction, cache release) pull the
    estimate down the same way growth pulls it up."""

    __slots__ = ("alpha", "bps", "_last_total", "_last_t")

    def __init__(self, alpha: float = 0.3):
        self.alpha = alpha
        self.bps: Optional[float] = None
        self._last_total: Optional[int] = None
        self._last_t = 0.0

    def update(self, total: int, now: float) -> None:
        if self._last_total is None:
            self._last_total, self._last_t = total, now
            return
        dt = now - self._last_t
        if dt <= 1e-6:
            # same instant: keep the OLD anchor so this growth folds into
            # the next measurable delta instead of being dropped
            return
        inst = (total - self._last_total) / dt
        self.bps = inst if self.bps is None else (
            self.alpha * inst + (1.0 - self.alpha) * self.bps)
        self._last_total, self._last_t = total, now


# -- the ledger ---------------------------------------------------------------


class MemoryLedger:
    """The process-wide byte ledger. ``stamp_device`` is the write-path
    entry (one lock, a small dict — analytic, zero syncs); ``summary()``
    is the on-demand /debug/memory body; host/disk totals refresh pulled
    and throttled. Alerts are per-scope fire-once transitions (the
    quality-auditor idiom)."""

    def __init__(self, metrics=None, window_s: float = 300.0,
                 headroom_alert_pct: float = 10.0,
                 device_budget_bytes: int = 0,
                 host_budget_bytes: int = 0):
        self.metrics = metrics
        self.window_s = max(float(window_s), 1e-3)
        self.headroom_alert_pct = float(headroom_alert_pct)
        self.device_budget_bytes = int(device_budget_bytes)
        self.host_budget_bytes = int(host_budget_bytes)
        self._lock = threading.Lock()
        # id(owner) -> (weakref, {component: bytes}, ndev)
        self._device: dict = {}
        self._rates = {s: _Rate() for s in SCOPES}
        self._alert_state = {s: False for s in SCOPES}
        self._alert_last_log: dict = {}
        self._alerts_fired = {s: 0 for s in SCOPES}
        # write-path lifecycle window: phase -> deque[(t, ms, rows, bytes)]
        self._write: dict = {p: deque(maxlen=_WRITE_SAMPLES_MAX)
                             for p in WRITE_PHASES}
        self._publish_lag: deque = deque(maxlen=_WRITE_SAMPLES_MAX)
        self._shapes: dict = {}  # shape key -> first-seen monotonic
        # lifetime counters (never evicted; clear() keeps them, perf idiom)
        self._rows_written = 0
        self._bytes_written = 0
        self._cow_copy_bytes = 0
        self._cow_peak: deque = deque(maxlen=1024)  # (t, transient bytes)
        self._publishes = 0
        self._stamps = 0
        # cached/refreshed host+disk views (throttled on the stamp path)
        self._host_cache: dict = {}
        self._disk_cache: dict = {}
        self._last_host_refresh = 0.0
        self._last_disk_refresh = 0.0
        self._disk_total = 0
        self._disk_path: Optional[str] = None
        self._auto_device_budget: Optional[int] = None
        self._auto_host_budget: Optional[int] = None

    # -- wiring --------------------------------------------------------------

    def set_disk_path(self, path: str) -> None:
        """The data volume whose usage backs the disk scope."""
        self._disk_path = path

    # -- device stamping (the write-path entry; zero device syncs) -----------

    def stamp_device(self, owner, components: dict, ndev: int = 1) -> None:
        """Replace ``owner``'s device components atomically. Called at
        every IndexSnapshot publish / mesh slab mutation with analytic
        shape x dtype sizes; an empty dict (drop) zeroes the owner out.
        Never called on the search path (spy-pinned)."""
        now = time.monotonic()
        pulled = device_provider_components()
        with self._lock:
            self._prune_device_locked()
            self._device[id(owner)] = (
                weakref.ref(owner), dict(components), max(int(ndev), 1))
            totals, per_dev = self._device_totals_locked(pulled)
            self._rates["device"].update(per_dev, now)
            self._stamps += 1
        self._set_component_gauges("device", totals, DEVICE_COMPONENTS)
        self._eval_scope("device", per_dev, self._device_budget())
        self._maybe_refresh(now)

    def _prune_device_locked(self) -> None:
        dead = [k for k, (r, _, _) in self._device.items() if r() is None]
        for k in dead:
            self._device.pop(k, None)

    def _device_totals_locked(self, pulled: Optional[dict] = None) -> tuple:
        """-> ({component: bytes} with foreign names folded into "other",
        per-device bytes). Per-device assumes mesh slabs spread evenly
        over their ndev chips (they do — _assign_balanced level-fills).
        ``pulled`` merges device-provider components (filter-words
        caches; small, counted at ndev=1)."""
        totals: dict = {}
        per_dev = 0.0
        for _, comps, ndev in self._device.values():
            for name, b in comps.items():
                label = name if name in DEVICE_COMPONENTS else OTHER
                totals[label] = totals.get(label, 0) + int(b)
            per_dev += sum(int(b) for b in comps.values()) / ndev
        for name, b in (pulled or {}).items():
            label = name if name in DEVICE_COMPONENTS else OTHER
            totals[label] = totals.get(label, 0) + int(b)
            per_dev += int(b)
        return totals, int(per_dev)

    def device_components(self) -> dict:
        pulled = device_provider_components()
        with self._lock:
            self._prune_device_locked()
            totals, _ = self._device_totals_locked(pulled)
        return totals

    def device_bytes_total(self) -> int:
        return sum(self.device_components().values())

    # -- host / disk refresh --------------------------------------------------

    def _maybe_refresh(self, now: float) -> None:
        if now - self._last_host_refresh >= _REFRESH_MIN_S:
            self.refresh_host(now)
        if self._disk_path and now - self._last_disk_refresh >= _REFRESH_MIN_S:
            self.refresh_disk(now)

    def refresh_host(self, now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        comps = host_components()
        total = sum(comps.values())
        with self._lock:
            self._host_cache = comps
            self._last_host_refresh = now
            self._rates["host"].update(total, now)
        self._set_component_gauges("host", comps, HOST_COMPONENTS)
        self._eval_scope("host", total, self._host_budget())
        return comps

    def refresh_disk(self, now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        path = self._disk_path
        if not path:
            return {}
        try:
            u = shutil.disk_usage(path)
        except OSError:
            return {}
        comps = {"used": int(u.used), "free": int(u.free)}
        # registered disk consumers (the incident-bundle directory): their
        # bytes are a sub-account of `used`, shown as their own component
        comps.update(_poll(_disk_providers))
        with self._lock:
            self._disk_cache = comps
            # one budget basis everywhere: the volume's total as reported
            # here backs BOTH the alert evaluation and summary()'s
            # forecast (used+free can undercount reserved blocks)
            self._disk_total = int(u.total)
            self._last_disk_refresh = now
            self._rates["disk"].update(int(u.used), now)
        self._set_component_gauges("disk", comps, DISK_COMPONENTS)
        self._eval_scope("disk", int(u.used), int(u.total))
        return comps

    def host_totals(self, refresh: bool = True) -> dict:
        if refresh:
            return self.refresh_host()
        with self._lock:
            return dict(self._host_cache)

    # -- budgets --------------------------------------------------------------

    def _device_budget(self) -> int:
        """Per-device HBM budget: the config override, else the backend's
        reported limit (``memory_stats()['bytes_limit']``), else 0 =
        unknown (no headroom/forecast for the scope). Detected once,
        lazily — never on a dispatch path."""
        if self.device_budget_bytes:
            return self.device_budget_bytes
        if self._auto_device_budget is None:
            budget = 0
            try:
                import jax

                stats = jax.local_devices()[0].memory_stats()
                if stats:
                    budget = int(stats.get("bytes_limit", 0))
            except Exception:  # noqa: BLE001 — absent backend support
                budget = 0
            self._auto_device_budget = budget
        return self._auto_device_budget

    def _host_budget(self) -> int:
        if self.host_budget_bytes:
            return self.host_budget_bytes
        if self._auto_host_budget is None:
            budget = 0
            try:
                with open("/proc/meminfo") as f:
                    for line in f:
                        if line.startswith("MemTotal:"):
                            budget = int(line.split()[1]) * 1024
                            break
            except OSError:
                budget = 0
            self._auto_host_budget = budget
        return self._auto_host_budget

    # -- headroom + fire-once alerts ------------------------------------------

    def _eval_scope(self, scope: str, used: int, budget: int) -> None:
        if budget <= 0:
            return
        headroom_pct = max(100.0 * (budget - used) / budget, 0.0)
        m = self.metrics
        if m is not None:
            try:
                m.memory_headroom.labels(scope).set(round(headroom_pct, 2))
            except Exception:  # noqa: BLE001 — metrics must not break writes
                pass
        degraded = headroom_pct < self.headroom_alert_pct
        with self._lock:
            transitioned = self._alert_state[scope] != degraded
            self._alert_state[scope] = degraded
            if degraded and transitioned:
                self._alerts_fired[scope] += 1
        if degraded:
            if transitioned and m is not None:
                try:
                    m.memory_alerts.labels(scope).inc()
                except Exception:  # noqa: BLE001
                    pass
            if transitioned:
                # the exhaustion transition is an ops-journal event AND an
                # incident trigger (monitoring/incidents.py): the bundle
                # preserves the byte ledger + forecast around the alert —
                # the post-mortem an HBM-OOM rc=3 never left behind. Lazy
                # import; one-comparison no-ops when the plane is off.
                try:
                    from weaviate_tpu.monitoring import incidents

                    incidents.emit("memory_alert", scope=scope,
                                   used_bytes=int(used),
                                   budget_bytes=int(budget),
                                   headroom_pct=round(headroom_pct, 2))
                    incidents.trigger(
                        "memory_exhaustion",
                        reason=f"memory headroom degraded: scope={scope} "
                               f"headroom={headroom_pct:.1f}% < "
                               f"{self.headroom_alert_pct:.1f}%",
                        detail={"scope": scope, "used_bytes": int(used),
                                "budget_bytes": int(budget)})
                except Exception:  # noqa: BLE001 — must not break the write path
                    pass
            now = time.monotonic()
            last = self._alert_last_log.get(scope)
            if transitioned or last is None \
                    or now - last >= ALERT_LOG_INTERVAL_S:
                self._alert_last_log[scope] = now
                fc = self.forecast_scope(scope, used, budget)
                tte = fc.get("tte_s")
                _LOG.warning(
                    "memory headroom degraded: scope=%s used=%d budget=%d "
                    "headroom=%.1f%% (< %.1f%%)%s — counted in "
                    "weaviate_memory_exhaustion_alerts_total; further "
                    "lines rate-limited to one per %.0fs",
                    scope, used, budget, headroom_pct,
                    self.headroom_alert_pct,
                    f", est. exhaustion in {tte:.0f}s" if tte else "",
                    ALERT_LOG_INTERVAL_S)
        elif transitioned:
            _LOG.info("memory headroom recovered: scope=%s headroom=%.1f%%",
                      scope, headroom_pct)
            try:
                from weaviate_tpu.monitoring import incidents

                incidents.emit("memory_recovered", scope=scope,
                               headroom_pct=round(headroom_pct, 2))
            except Exception:  # noqa: BLE001 — must not break the write path
                pass

    def forecast_scope(self, scope: str, used: int, budget: int) -> dict:
        """One scope's forecast: headroom, ingest-rate EWMA, and the
        time-to-exhaustion estimate (None when not growing or unbudgeted)."""
        with self._lock:
            rate = self._rates[scope].bps
            alert = self._alert_state[scope]
            fired = self._alerts_fired[scope]
        out: dict = {
            "used_bytes": int(used),
            "budget_bytes": int(budget),
            "headroom_pct": round(max(100.0 * (budget - used) / budget, 0.0), 2)
            if budget > 0 else None,
            "ingest_bps": round(rate, 1) if rate is not None else None,
            "tte_s": None,
            "alert": alert,
            "alerts_fired": fired,
        }
        if budget > used and rate is not None and rate > 1e-9:
            out["tte_s"] = round((budget - used) / rate, 1)
        return out

    # -- write-path lifecycle -------------------------------------------------

    def note_write(self, op: str, phase: str, ms: float, rows: int = 0,
                   bytes_moved: int = 0) -> None:
        """One write-path phase completion (flush, device_write,
        apply_tombstones, compress, compact) with its rows/bytes moved."""
        now = time.monotonic()
        with self._lock:
            d = self._write.get(phase)
            if d is None:
                d = self._write[phase] = deque(maxlen=_WRITE_SAMPLES_MAX)
            d.append((now, float(ms), int(rows), int(bytes_moved)))
            self._rows_written += int(rows)
            self._bytes_written += int(bytes_moved)
        m = self.metrics
        if m is not None and phase in ("flush", "device_write"):
            try:
                m.write_flush.observe(float(ms))
            except Exception:  # noqa: BLE001
                pass

    def note_cow(self, copied_bytes: int, transient_peak: int = 0) -> None:
        """COW accounting: ``copied_bytes`` counts host arrays duplicated
        for a pinned snapshot; ``transient_peak`` records the device-side
        extra bytes a non-donating write holds while old and new buffer
        generations are both alive."""
        now = time.monotonic()
        with self._lock:
            if copied_bytes:
                self._cow_copy_bytes += int(copied_bytes)
            if transient_peak:
                self._cow_peak.append((now, int(transient_peak)))
        m = self.metrics
        if m is not None and copied_bytes:
            try:
                m.cow_copy_bytes.inc(int(copied_bytes))
            except Exception:  # noqa: BLE001
                pass

    def note_publish(self, staged_lag_ms: float) -> None:
        """Snapshot publication: how long the oldest staged (unpublished)
        mutation waited — the read-your-writes flush debt."""
        now = time.monotonic()
        with self._lock:
            self._publish_lag.append((now, float(staged_lag_ms)))
            self._publishes += 1

    def note_write_shape(self, key: tuple) -> None:
        """First sighting of a write-kernel shape (a compile proxy — the
        write-path twin of the trace plane's jit_shape_first_seen)."""
        with self._lock:
            if key in self._shapes or len(self._shapes) >= _SHAPES_MAX:
                return
            self._shapes[key] = time.monotonic()

    # -- gauges ---------------------------------------------------------------

    def _set_component_gauges(self, scope: str, totals: dict,
                              taxonomy: tuple) -> None:
        m = self.metrics
        if m is None:
            return
        vec = {"device": getattr(m, "device_bytes", None),
               "host": getattr(m, "host_bytes", None),
               "disk": getattr(m, "disk_bytes", None)}.get(scope)
        if vec is None:
            return
        try:
            # the full taxonomy is always written so a component that
            # vanished (compress dropped the float store) reads 0, never
            # its stale last value
            for name in taxonomy + (OTHER,):
                vec.labels(name).set(totals.get(name, 0))
        except Exception:  # noqa: BLE001 — metrics must not break writes
            pass

    # -- introspection --------------------------------------------------------

    def _write_window_locked(self, now: float) -> dict:
        horizon = now - self.window_s
        phases: dict = {}
        for name in WRITE_PHASES:
            d = self._write.get(name)
            if not d:
                continue
            vals = [(ms, rows, b) for t, ms, rows, b in d if t >= horizon]
            if not vals:
                continue
            svals = sorted(v[0] for v in vals)
            phases[name] = {
                "samples": len(svals),
                "p50_ms": round(_pct(svals, 50.0), 3),
                "p99_ms": round(_pct(svals, 99.0), 3),
                "rows": sum(v[1] for v in vals),
                "bytes": sum(v[2] for v in vals),
            }
        lags = sorted(ms for t, ms in self._publish_lag if t >= horizon)
        peaks = [b for t, b in self._cow_peak if t >= horizon]
        out = {
            "phases": phases,
            "rows_written_total": self._rows_written,
            "bytes_written_total": self._bytes_written,
            "cow_copy_bytes_total": self._cow_copy_bytes,
            "cow_transient_peak_bytes": max(peaks) if peaks else 0,
            "publishes_total": self._publishes,
        }
        if lags:
            out["staged_publish_lag_ms"] = {
                "p50": round(_pct(lags, 50.0), 3),
                "p99": round(_pct(lags, 99.0), 3),
            }
        return out

    def _device_stats_drift(self) -> Optional[dict]:
        """Allocator cross-check where the backend provides it: the drift
        between what the ledger accounts and what the device allocator
        reports in use (includes XLA workspace/executable overhead the
        analytic ledger deliberately does not model — a gauge to watch,
        never the primary). Summary-time only."""
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
        except Exception:  # noqa: BLE001 — absent backend support
            return None
        if not stats or "bytes_in_use" not in stats:
            return None
        in_use = int(stats["bytes_in_use"])
        pulled = device_provider_components()
        with self._lock:
            _, per_dev = self._device_totals_locked(pulled)
        drift = in_use - per_dev
        m = self.metrics
        if m is not None:
            try:
                m.memory_drift.labels("device").set(drift)
            except Exception:  # noqa: BLE001
                pass
        return {"allocator_bytes_in_use": in_use,
                "ledger_per_device_bytes": per_dev,
                "drift_bytes": drift}

    def summary(self) -> dict:
        """The /debug/memory body: device/host/disk component tables +
        budgets + headroom, the write-lifecycle window, the per-scope
        exhaustion forecast, write-shape first-seen facts, and the
        allocator drift cross-check."""
        now = time.monotonic()
        host = self.refresh_host(now)
        disk = self.refresh_disk(now)
        pulled = device_provider_components()
        with self._lock:
            self._prune_device_locked()
            dev_totals, per_dev = self._device_totals_locked(pulled)
            write = self._write_window_locked(now)
            shapes = sorted(
                ((now - t, key) for key, t in self._shapes.items()))
            stamps = self._stamps
        dev_budget = self._device_budget()
        host_budget = self._host_budget()
        disk_total = self._disk_total  # same basis the alert evaluated
        out: dict = {
            "window_s": self.window_s,
            "headroom_alert_pct": self.headroom_alert_pct,
            "stamps": stamps,
            "device": {
                "components": dict(sorted(dev_totals.items(),
                                          key=lambda kv: -kv[1])),
                "total_bytes": sum(dev_totals.values()),
                "per_device_bytes": per_dev,
                "budget_bytes": dev_budget or None,
            },
            "host": {
                "components": dict(sorted(host.items(),
                                          key=lambda kv: -kv[1])),
                "total_bytes": sum(host.values()),
                "budget_bytes": host_budget or None,
            },
            "disk": {
                "components": disk,
                "path": self._disk_path,
                "total_bytes": disk_total or None,
            },
            "write": write,
            "forecast": {
                "device": self.forecast_scope("device", per_dev, dev_budget),
                "host": self.forecast_scope("host", sum(host.values()),
                                            host_budget),
                "disk": self.forecast_scope("disk", disk.get("used", 0),
                                            disk_total),
            },
            "jit_first_seen": [
                {"shape": list(key), "age_s": round(age, 1)}
                for age, key in shapes[:32]],
        }
        drift = self._device_stats_drift()
        if drift is not None:
            out["device"]["allocator"] = drift
        return out

    def bench_block(self) -> dict:
        """The compact ``memory`` block bench rows carry."""
        doc = self.summary()
        fc = doc["forecast"]
        return {
            "device_bytes": doc["device"]["total_bytes"],
            "device_components": doc["device"]["components"],
            "host_bytes": doc["host"]["total_bytes"],
            "headroom_pct": {s: fc[s].get("headroom_pct") for s in SCOPES},
            "ingest_bps": {s: fc[s].get("ingest_bps") for s in SCOPES},
            "tte_s": {s: fc[s].get("tte_s") for s in SCOPES},
            "cow_copy_bytes": doc["write"]["cow_copy_bytes_total"],
            "rows_written": doc["write"]["rows_written_total"],
        }

    def clear(self) -> None:
        """Reset the rolling write window, rates, and alert states (bench
        measurement slices). Current device/host component state is live
        state, not window state — it survives, as do lifetime counters."""
        with self._lock:
            for d in self._write.values():
                d.clear()
            self._publish_lag.clear()
            self._cow_peak.clear()
            self._shapes.clear()
            self._rates = {s: _Rate() for s in SCOPES}
            self._alert_state = {s: False for s in SCOPES}
            self._alert_last_log.clear()


# -- module state + zero-hop accessors ----------------------------------------

_ledger: Optional[MemoryLedger] = None

# final summaries of recently-unconfigured ledgers (CI failure artifact:
# tests/conftest.py dumps these to debug_memory.json beside the perf and
# quality stashes). Guarded by its own lock — concurrent App teardowns
# share it (the perf.py pattern).
_final_summaries: deque = deque(maxlen=8)
_summaries_lock = threading.Lock()


def configure(ledger: Optional[MemoryLedger]) -> Optional[MemoryLedger]:
    """Install (or clear, with None) the process-wide memory ledger."""
    global _ledger
    _ledger = ledger
    return ledger


def unconfigure(ledger: MemoryLedger) -> None:
    """Clear the global only if it is still `ledger` (App shutdown must
    not tear down a newer App's ledger); stash its final summary for the
    CI artifact dump when it saw any activity."""
    global _ledger
    try:
        if ledger._stamps > 0 or ledger._rows_written > 0:
            doc = ledger.summary()
            with _summaries_lock:
                _final_summaries.append(doc)
    except Exception:  # noqa: BLE001 — teardown must never fail shutdown
        pass
    if _ledger is ledger:
        _ledger = None


def get_ledger() -> Optional[MemoryLedger]:
    return _ledger


def recent_summaries() -> list:
    """Final summaries of ledgers torn down this process (newest last),
    plus the live ledger's current summary when one is installed."""
    with _summaries_lock:
        out = list(_final_summaries)
    led = _ledger
    if led is not None:
        try:
            out.append(led.summary())
        except Exception:  # noqa: BLE001
            pass
    return out
