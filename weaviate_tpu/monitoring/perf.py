"""Continuous device-performance attribution: the rolling perf window.

The r05 chip session measured 13.7k QPS at 1.78% MFU on ONE manual
profile; the hypothesis — host-side gather/rescore and per-dispatch
orchestration dominate — needs a *continuous* measurement so the fused
multi-stage search (ROADMAP items 1-3) gets a real before/after. This
module aggregates what the dispatch plane records:

- every device dispatch's analytic cost (costmodel.DispatchShape: flops,
  bytes, tier) and host-overhead ledger (enqueue / device fetch /
  gather hop / hydrate), fed by db/shard.py for EVERY dispatch while the
  tracer is up — full coverage, independent of trace sampling;
- per-request queue waits and per-dispatch scatter times from the
  coalescer (``note_phase``);
- the **device duty cycle**: the fraction of wall-clock with an in-flight
  device dispatch, integrated from [enqueue-start, fetch-end] intervals.
  kernel-level MFU high + duty cycle low = the orchestration gap; both
  high = the kernel itself is the limit. This is the number that directly
  tests the orchestration-gap hypothesis.

Exposure: rolling-window Prometheus gauges (``weaviate_device_mfu_pct``,
``weaviate_device_hbm_bw_pct``, ``weaviate_device_duty_cycle``), a
per-dispatch phase-share histogram (``weaviate_perf_phase_share``), the
``GET /debug/perf`` window summary (server/rest.py, same authorizer as
pprof), and the ``roofline``/``duty_cycle``/``phase_share`` fields on
bench.py serving rows.

Lifecycle mirrors the tracer (monitoring/tracing.py): a process-wide
module global installed by App when TRACING_ENABLED is set, None
otherwise — every serving-path entry point is then a one-comparison
no-op and constructs nothing (spy-pinned in tests/test_perf.py).

The QUALITY twin of this window lives in monitoring/quality.py: the
shadow recall auditor measures what the serving path ANSWERS (recall,
rank overlap, distance error at ``GET /debug/quality``) the way this
window measures what it COSTS — same rolling-window idiom, same
zero-cost-disabled lifecycle, same authorizer.

The CAPSTONE consumer is the incident plane (monitoring/incidents.py):
``summary()`` is captured verbatim into every flight-recorder bundle, so
a breaker trip or SLO burn preserves the window's duty-cycle/roofline/
ledger picture at the moment of the incident — and
``recent_summaries()`` keeps the last windows reachable even after the
owning App is torn down (the bench's rc=3 emergency dump reads it).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from weaviate_tpu.monitoring import costmodel

# ledger stages in display order (the /debug/perf breakdown; scatter is
# fed by the coalescer, queue_wait per admitted request)
PHASES = ("queue_wait", "filter", "enqueue", "device", "gather_hop",
          "hydrate", "scatter")

# per-phase sample cap (deque maxlen): queue_wait gets one sample per
# ADMITTED REQUEST, so a 60 s window at r05-scale QPS (~13.7k/s) would
# otherwise retain ~800k tuples and every summary() would copy+sort them
# under the window lock. Percentiles are over the most recent samples
# within the window — plenty for p50/p99 at any realistic horizon.
_PHASE_SAMPLES_MAX = 16384


class DutyCycle:
    """Busy-time integrator over [start, end) intervals within a rolling
    window. Incremental: each recorded interval contributes only the part
    not already covered by earlier intervals (``busy_until`` carries the
    merge frontier), so overlapping concurrent dispatches never double
    count. Exact for intervals arriving in nondecreasing START order; a
    deep pipeline that completes out of order can under-count the overlap
    by at most the reorder window (documented in docs/performance.md)."""

    __slots__ = ("window_s", "_deltas", "_busy_until", "_busy_total",
                 "_first_t")

    def __init__(self, window_s: float):
        self.window_s = max(float(window_s), 1e-3)
        # (t_end, busy_delta): busy time attributed at interval end, plus
        # a running total — value() must be O(evictions), not O(window),
        # because record_dispatch calls it per dispatch under the window
        # lock on the serving path
        self._deltas: deque = deque()
        self._busy_total = 0.0
        self._busy_until = 0.0
        self._first_t: Optional[float] = None

    def record(self, start: float, end: float) -> None:
        if end <= start:
            return
        if self._first_t is None:
            self._first_t = start
        covered_from = max(start, self._busy_until)
        delta = max(end - covered_from, 0.0)
        self._busy_until = max(self._busy_until, end)
        if delta > 0.0:
            self._deltas.append((end, delta))
            self._busy_total += delta

    def _trim(self, now: float) -> None:
        horizon = now - self.window_s
        while self._deltas and self._deltas[0][0] < horizon:
            _, d = self._deltas.popleft()
            self._busy_total -= d
        if not self._deltas:
            self._busy_total = 0.0  # no float-drift residue on empty

    def busy_s(self, now: Optional[float] = None) -> float:
        """Merged busy seconds within the trailing window. The PerfWindow
        divides this by ITS observed span so duty and the window roofline
        share one denominator."""
        now = time.monotonic() if now is None else now
        self._trim(now)
        return max(self._busy_total, 0.0)

    def value(self, now: Optional[float] = None) -> float:
        """Busy fraction of the trailing window (0..1). The denominator is
        the OBSERVED span — min(window_s, now - first interval) — so a
        window that just started reports its live fraction instead of
        diluting against unobserved time."""
        now = time.monotonic() if now is None else now
        busy = self.busy_s(now)
        if self._first_t is None:
            return 0.0
        span = min(self.window_s, max(now - self._first_t, 1e-9))
        return min(busy / span, 1.0)


class PerfWindow:
    """Rolling-window aggregate of dispatch cost + host-overhead ledgers.

    ``record_dispatch`` is the per-dispatch hot-path entry: one lock, O(1)
    amortized (eviction pops), gauge sets guarded so a broken metrics
    stack can never take down serving. ``summary()`` is the on-demand
    /debug/perf body."""

    def __init__(self, window_s: float = 60.0, metrics=None,
                 backend: Optional[str] = None,
                 sample_hint: float = 1.0):
        self.window_s = max(float(window_s), 1e-3)
        self.metrics = metrics
        self.backend = backend or costmodel.detect_backend()
        # trace sample rate, surfaced in the summary: dispatch coverage
        # here is FULL (shard feeds every dispatch while the tracer is
        # up), but readers correlating with /debug/traces need the rate
        self.sample_hint = float(sample_hint)
        self._lock = threading.Lock()
        # (t_end_mono, flops, bytes, device_s, wall_s, tier, regime, rows)
        self._entries: deque = deque()
        # phase name -> deque[(t_mono, ms)], count-capped (see
        # _PHASE_SAMPLES_MAX) on top of the time-horizon eviction
        self._phase: dict[str, deque] = {
            p: deque(maxlen=_PHASE_SAMPLES_MAX) for p in PHASES}
        self._duty = DutyCycle(self.window_s)
        # running sums over the live window (evicted incrementally)
        self._flops = 0
        self._bytes = 0
        self._device_s = 0.0
        self._rows = 0
        self._started = time.monotonic()
        self._first_entry: Optional[float] = None
        self._total_dispatches = 0  # lifetime, never evicted

    # -- hot path ------------------------------------------------------------

    def record_dispatch(self, shape, rows: int = 0) -> None:
        """Fold one finished device dispatch (a costmodel.DispatchShape
        with its ledger stamped) into the window. Called by db/shard.py
        for every dispatch while the perf plane is up."""
        now = time.monotonic()
        ledger = shape.ledger()
        device_s = max(shape.device_ms, 0.0) / 1000.0
        flops = shape.flops()
        byts = shape.bytes()
        # mesh dispatches (shape.ndev > 1) count GLOBAL work in n — the
        # whole sharded program's rows. The roofline compares achieved
        # rates against ONE chip's peak, so normalize to per-chip work;
        # arithmetic intensity (flops/bytes) is unchanged by the division,
        # so the regime classification stays identical
        nd = max(int(getattr(shape, "ndev", 1)), 1)
        if nd > 1:
            flops //= nd
            byts //= nd
        regime = (costmodel.regime(flops, byts, self.backend)
                  if device_s > 0.0 else None)
        # the shape's wall endpoints are perf_counter stamps; the window
        # runs on time.monotonic. Only DURATIONS are trusted
        # (clock-agnostic deltas); the in-flight interval — enqueue start
        # to FETCH end, the device-busy span — is anchored at the
        # monotonic fetch stamp `_fetch_packed` took (NOT at this record
        # call: hydration runs in between, and re-anchoring here would
        # shift concurrent dispatches' intervals by their differing
        # hydrate times and corrupt the overlap merge)
        wall_s = max(shape.t_end - shape.t_start, 0.0)
        # no fetch stamp = no device call ran (an empty gather-tier early
        # return): it must contribute NO duty interval — counting its
        # host-only wall as "device in flight" would read near-1.0 duty on
        # a workload whose device is idle, inverting the signal
        inflight_s = (max(shape.t_fetch - shape.t_start, 0.0)
                      if shape.t_fetch > 0.0 else 0.0)
        fetch_end = (shape.t_fetch_mono
                     if 0.0 < shape.t_fetch_mono <= now else now)
        fused = bool(shape.fused)
        # the fused-dispatch invariant (one blocking fetch, zero host
        # translation): violations are counted per window — a fused
        # dispatch quietly re-growing host translation work must be
        # dashboard-visible, not just test-pinned
        viol = not costmodel.fused_invariant_ok(shape)
        with self._lock:
            self._evict(now)
            self._entries.append(
                (now, flops, byts, device_s, shape.tier, regime,
                 int(rows) or shape.batch, fused, viol))
            self._flops += flops
            self._bytes += byts
            self._device_s += device_s
            self._rows += int(rows) or shape.batch
            self._total_dispatches += 1
            if self._first_entry is None:
                # anchor the observed span at this dispatch's START so
                # the first entry's window roofline divides by its own
                # wall, not by an epsilon
                self._first_entry = now - wall_s
            for name, ms in ledger.items():
                self._phase[name].append((now, ms))
            if inflight_s > 0.0:
                self._duty.record(fetch_end - inflight_s, fetch_end)
            duty = self._duty_locked(now)
            mfu, bw = self._window_roofline_locked(now)
        m = self.metrics
        if m is not None:
            try:
                m.device_duty_cycle.set(duty)
                m.device_mfu.set(mfu)
                m.device_hbm_bw.set(bw)
                total = sum(ledger.values())
                if total > 0.0:
                    for name, ms in ledger.items():
                        m.perf_phase_share.labels(name).observe(ms / total)
            except Exception:  # noqa: BLE001 — metrics must not break serving
                pass

    def note_phase(self, name: str, ms: float) -> None:
        """Record one sample of a ledger stage measured outside the shard
        dispatch (coalescer queue_wait per request, scatter per lane)."""
        now = time.monotonic()
        with self._lock:
            d = self._phase.get(name)
            if d is None:
                d = self._phase[name] = deque(maxlen=_PHASE_SAMPLES_MAX)
            d.append((now, float(ms)))
            # bound growth between dispatch-driven evictions (the maxlen
            # cap bounds the worst case regardless)
            horizon = now - self.window_s
            while d and d[0][0] < horizon:
                d.popleft()

    def _evict(self, now: float) -> None:
        horizon = now - self.window_s
        while self._entries and self._entries[0][0] < horizon:
            _, f, b, ds, _, _, r, _, _ = self._entries.popleft()
            self._flops -= f
            self._bytes -= b
            self._device_s -= ds
            self._rows -= r
        for d in self._phase.values():
            while d and d[0][0] < horizon:
                d.popleft()

    def _observed_span(self, now: float) -> float:
        if self._first_entry is None:
            return 0.0
        return min(self.window_s, max(now - self._first_entry, 1e-9))

    def _duty_locked(self, now: float) -> float:
        """Duty over the window's OWN observed span — one denominator for
        duty, busy seconds, and the wall roofline (a fetch-anchored
        interval may predate the first record; clamping keeps the three
        mutually consistent)."""
        span = self._observed_span(now)
        if span <= 0.0:
            return 0.0
        return min(self._duty.busy_s(now) / span, 1.0)

    def _window_roofline_locked(self, now: float) -> tuple:
        """(wall mfu_pct, wall bw_pct) over the observed window span —
        the serving-level numbers comparable to the bench/r05 rows."""
        span = self._observed_span(now)
        if span <= 0.0:
            return 0.0, 0.0
        peak = costmodel.PEAKS.get(self.backend, costmodel.PEAKS["cpu"])
        mfu = 100.0 * (self._flops / span / 1e12) / peak["tflops"]
        bw = 100.0 * (self._bytes / span / 1e9) / peak["hbm_gbs"]
        return round(mfu, 3), round(bw, 3)

    # -- introspection -------------------------------------------------------

    def control_signals(self) -> dict:
        """The cheap per-tick sensor read for the control plane's lane
        controller (serving/controller.py): duty cycle, mean queue wait,
        and the dispatch count over the window — means only, no
        percentile sorts, so a 1 Hz tick costs O(window samples) adds
        under the lock and nothing else."""
        now = time.monotonic()
        with self._lock:
            self._evict(now)
            qw = self._phase.get("queue_wait")
            qw_mean = (sum(ms for _, ms in qw) / len(qw)) if qw else 0.0
            return {
                "duty_cycle": round(self._duty_locked(now), 4),
                "queue_wait_mean_ms": round(qw_mean, 3),
                "dispatches": len(self._entries),
            }

    def clear(self) -> None:
        """Reset the window (bench measurement slices)."""
        with self._lock:
            self._entries.clear()
            for d in self._phase.values():
                d.clear()
            self._duty = DutyCycle(self.window_s)
            self._flops = self._bytes = 0
            self._device_s = 0.0
            self._rows = 0
            self._first_entry = None
            self._started = time.monotonic()

    def summary(self) -> dict:
        """The /debug/perf body: window roofline (wall-clock AND
        device-busy forms), duty cycle, per-phase p50/p99 + share of the
        accounted dispatch wall, tier/regime tallies."""
        now = time.monotonic()
        with self._lock:
            self._evict(now)
            span = self._observed_span(now)
            duty = self._duty_locked(now)
            n = len(self._entries)
            flops, byts = self._flops, self._bytes
            device_s, rows = self._device_s, self._rows
            phase_ms = {p: [ms for _, ms in d]
                        for p, d in self._phase.items() if d}
            tiers: dict[str, int] = {}
            regimes: dict[str, int] = {}
            fused_n = fused_viol = 0
            for _, _, _, _, tier, regime, _, fused, viol in self._entries:
                tiers[tier] = tiers.get(tier, 0) + 1
                if fused:
                    fused_n += 1
                if viol:
                    fused_viol += 1
                if regime:
                    regimes[regime] = regimes.get(regime, 0) + 1
            total_dispatches = self._total_dispatches
        busy_s = duty * span
        out: dict = {
            "window_s": self.window_s,
            "observed_s": round(span, 3),
            "backend": self.backend,
            "trace_sample_rate": self.sample_hint,
            "dispatches": n,
            "dispatches_lifetime": total_dispatches,
            "rows": rows,
            "duty_cycle": round(duty, 4),
            # union of in-flight (enqueue->fetch) intervals — the
            # device-busy roofline's denominator
            "device_busy_s": round(busy_s, 4),
            # sum of blocked-fetch times: a LOWER bound on device time
            # (a result landing during host overlap fetches in ~0 ms), so
            # it is reported but never used as a roofline denominator
            "device_fetch_s": round(device_s, 4),
        }
        # wall roofline: achieved over the observed window span — the
        # serving-level MFU (what r05's 1.78% measured). device-busy
        # roofline: the same work over only the in-flight seconds —
        # utilization while the device had a dispatch in flight
        # (wall mfu = duty_cycle x this). The gap between the two IS the
        # orchestration overhead the duty cycle measures.
        if span > 0.0 and flops > 0:
            out["roofline"] = costmodel.roofline(
                flops / span, byts / span, 1.0, self.backend)
            if busy_s > 0.0:
                out["roofline_device_busy"] = costmodel.roofline(
                    flops, byts, busy_s, self.backend)
        phases: dict = {}
        total_accounted = sum(sum(v) for v in phase_ms.values())
        for p in PHASES:
            vals = phase_ms.get(p)
            if not vals:
                continue
            svals = sorted(vals)
            phases[p] = {
                "samples": len(svals),
                "p50_ms": round(_pct(svals, 50.0), 3),
                "p99_ms": round(_pct(svals, 99.0), 3),
                "mean_ms": round(sum(svals) / len(svals), 3),
                "share_of_wall": round(sum(svals) / total_accounted, 4)
                if total_accounted > 0.0 else None,
            }
        out["phases"] = phases
        out["tiers"] = dict(sorted(tiers.items(), key=lambda kv: -kv[1]))
        out["regimes"] = dict(sorted(regimes.items(), key=lambda kv: -kv[1]))
        # fused-dispatch coverage + invariant violations over the window
        # (costmodel.fused_invariant_ok): share near 1.0 with violations 0
        # is the steady state; violations > 0 means host post-processing
        # crept back into a dispatch that claims device-side translation
        out["fused"] = {"dispatches": fused_n, "violations": fused_viol}
        return out


def _pct(sorted_vals: list, q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    i = min(int(len(sorted_vals) * q / 100.0), len(sorted_vals) - 1)
    return float(sorted_vals[i])


# -- module state + zero-hop accessors ----------------------------------------

_window: Optional[PerfWindow] = None

# final summaries of recently-unconfigured windows (CI failure artifact:
# tests/conftest.py dumps these so a red run's bundle carries the perf
# picture of the Apps the suite ran — bounded, newest last). Guarded by
# its own lock: concurrent App teardowns (test suites) share it.
_final_summaries: deque = deque(maxlen=8)
_summaries_lock = threading.Lock()


def configure(window: Optional[PerfWindow]) -> Optional[PerfWindow]:
    """Install (or clear, with None) the process-wide perf window."""
    global _window
    _window = window
    return window


def unconfigure(window: PerfWindow) -> None:
    """Clear the global only if it is still `window` (App shutdown must
    not tear down a newer App's window); stash its final summary for the
    CI artifact dump when it saw any dispatches."""
    global _window
    try:
        if window._total_dispatches > 0:
            doc = window.summary()
            with _summaries_lock:
                _final_summaries.append(doc)
    except Exception:  # noqa: BLE001 — teardown must never fail shutdown
        pass
    if _window is window:
        _window = None


def get_window() -> Optional[PerfWindow]:
    return _window


def recent_summaries() -> list:
    """Final summaries of windows torn down this process (newest last),
    plus the live window's current summary when one is installed."""
    with _summaries_lock:
        out = list(_final_summaries)
    w = _window
    if w is not None:
        try:
            out.append(w.summary())
        except Exception:  # noqa: BLE001
            pass
    return out
