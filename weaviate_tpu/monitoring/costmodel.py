"""Analytic device cost model: FLOPs/bytes per dispatch vs platform peaks.

One roofline model shared by every consumer — bench.py's offline matrix
rows, the serving path's per-dispatch attribution (monitoring/tracing.py
DispatchRecord facts), the rolling perf window behind ``/debug/perf``
(monitoring/perf.py), and the BM25 device engine's batch-shape recording
(inverted/bm25_device.py). Before this module the model lived only in
bench.py (``PEAKS``/``_roofline``) plus an ad-hoc stats dict in the BM25
engine, so the serving path could not say where a dispatch sat against the
hardware; now bench and serving compute the same numbers from the same
formulas.

Conventions (inherited from the bench model, kept deliberately):

- FLOPs are the *useful* distance math — ``2 · B · N · D`` per scan batch
  (the matmul at the heart of every tier) — not implementation FLOPs, so
  MFU is comparable across tiers (PQ's reconstruction-as-matmul does more
  hardware FLOPs to serve the same distance work).
- Bytes are the store bytes actually read from HBM per batch (queries,
  LUTs, and top-k buffers are noise at these shapes): ``N · bytes_per_row``
  with bytes_per_row = 4·D for the f32 store, 2·D for the bf16 rescore
  copy, M (segments) for codes-only PQ.
- Arithmetic intensity is therefore ``2·B / bytes_per_elem``: the batch
  width decides the regime, which is why batch-first serving (the
  coalescer) is the design lever.

Peaks are the public v5e datasheet figures; the CPU entry is a *nominal*
single-socket estimate so cpu-backend rows carry the same fields — cpu
mfu_pct is a proxy, not a claim. The module imports only the stdlib
(platform detection imports jax lazily and caches), so index/db/serving
layers can import it without cycles or backend init.
"""

from __future__ import annotations

import os
from typing import Optional

# -- platform peaks -----------------------------------------------------------

PEAKS = {
    "tpu-v5e": {"tflops": 197.0, "hbm_gbs": 819.0,
                "note": "v5e peaks: 197 bf16 TFLOP/s MXU, 819 GB/s HBM"},
    "cpu": {"tflops": 0.096 * (os.cpu_count() or 1), "hbm_gbs": 25.0,
            "note": (f"nominal CPU peaks ({os.cpu_count() or 1} core(s) x "
                     "96 GFLOP/s AVX2+FMA, 25 GB/s DRAM) — proxy only")},
}

_detected_backend: Optional[str] = None


def backend_for_platform(platform: str) -> str:
    """jax platform name -> PEAKS key ("axon" is the relay's name for the
    same v5e hardware — one backend vocabulary, like bench.py's rows)."""
    return "tpu-v5e" if platform in ("tpu", "axon") else "cpu"


def detect_backend() -> str:
    """PEAKS key for the live jax backend, cached after the first call.
    Never initializes a backend by surprise on an import path: falls back
    to "cpu" when jax (or a device) is unavailable."""
    global _detected_backend
    if _detected_backend is None:
        try:
            import jax  # noqa: PLC0415 — lazy: stdlib-only module import

            _detected_backend = backend_for_platform(jax.default_backend())
        except Exception:  # noqa: BLE001 — no backend => nominal CPU peaks
            _detected_backend = "cpu"
    return _detected_backend


# -- dispatch tiers -----------------------------------------------------------

# the serving read tiers of index/tpu.py _dispatch_search, plus the BM25
# device engine's batched matmul — the `tier` fact on dispatch traces and
# the top-tier tally in /debug/perf
TIER_EXACT = "exact_scan"            # full f32 (or bf16-store) scan
TIER_PQ_RESCORE = "pq_rescore_bf16"  # PQ with rescore: scans the bf16 copy
TIER_PQ_CODES = "pq_codes"           # codes-only ADC (gmin / recon / LUT)
TIER_PQ_ADC4 = "pq_adc4"             # 4-bit funnel: nibble scan + re-rank
TIER_GATHER = "gather"               # small-allowList gathered row scoring
TIER_BM25_MATMUL = "bm25_matmul"     # dense-row keyword batch matmul


class DispatchShape:
    """The analytic shape of ONE device dispatch, plus the host-overhead
    ledger timings the index stamps while executing it.

    Built on the serving path ONLY while the tracer is up (index/tpu.py
    gates construction on ``tracing.get_tracer()``), so the disabled
    serving path constructs zero of these — the same contract as spans.

    Analytic fields (set at construction):
      tier           one of the TIER_* constants
      n              rows the dispatch scans (live rows; the allowList size
                     on the gather tier; n_pad on the BM25 matmul; on an
                     IVF partition-pruned dispatch the PROBED rows —
                     top_p x bucket capacity, plus the nlist centroid
                     rows — so flops()/bytes() are probed-aware and the
                     roofline never reports the phantom work of the rows
                     the probe skipped; ``extra`` then carries
                     {"ivf": True, "probed_fraction": probed/N})
      dim            vector dims (effective units for BM25)
      batch          ACTUAL query rows (useful work — padding is reported
                     separately, never smeared; the PR-3 convention)
      batch_padded   device dispatch width after bucket padding
      bytes_per_row  HBM bytes read per scanned row
      k              selection depth

    Ledger fields (stamped by the index/shard while the dispatch runs;
    ms, -1 = not measured):
      enqueue_ms     host time building + enqueueing the device work
                     (query prep, allowList pack, host gather)
      device_ms      the ONE blocking device->host fetch (finalize)
      finalize_ms    whole finalize() wall — device_ms + the host hop
      filter_ms      allowList build (shard, filtered dispatches)
      hydrate_ms     LSM result hydration (shard)
    and the monotonic interval [t_start, t_end] from enqueue start to
    fetch end — the in-flight-device interval the duty cycle integrates.
    """

    __slots__ = ("tier", "n", "dim", "batch", "batch_padded",
                 "bytes_per_row", "k", "extra", "ndev",
                 "enqueue_ms", "device_ms", "finalize_ms",
                 "filter_ms", "hydrate_ms", "t_start", "t_end",
                 "t_fetch", "t_fetch_mono", "fused", "fetches",
                 "translate_ms")

    def __init__(self, tier: str, n: int, dim: float, batch: int,
                 bytes_per_row: float, k: int = 0,
                 batch_padded: int = 0, extra: Optional[dict] = None,
                 ndev: int = 1):
        self.tier = tier
        self.n = int(n)
        self.dim = dim
        self.batch = int(batch)
        self.batch_padded = int(batch_padded) or int(batch)
        self.bytes_per_row = bytes_per_row
        self.k = int(k)
        self.extra = extra
        # devices the SPMD program spans (mesh dispatches): `n` stays the
        # GLOBAL row count so flops()/bytes() keep reporting whole-dispatch
        # work; per-chip attribution divides by ndev (monitoring/perf.py)
        self.ndev = max(int(ndev), 1)
        self.enqueue_ms = -1.0
        self.device_ms = -1.0
        self.finalize_ms = -1.0
        self.filter_ms = -1.0
        self.hydrate_ms = -1.0
        self.t_start = 0.0
        self.t_end = 0.0
        # fetch-end stamps (index _fetch_packed): perf_counter for the
        # in-flight duration, monotonic for the duty-cycle anchor (the
        # perf window runs on time.monotonic — hydration happens between
        # fetch end and the window's record call, so the record time is
        # NOT a usable anchor)
        self.t_fetch = 0.0
        self.t_fetch_mono = 0.0
        # fused-dispatch ledger (index/tpu.py): `fused` marks a dispatch
        # whose program emitted final doc ids (slot->doc translation on
        # device), `fetches` counts blocking device->host fetches
        # (_fetch_packed), and `translate_ms` is the measured host-side
        # slot->doc translation — stamped 0.0 at dispatch on the fused
        # path (nothing to measure, by construction), measured on the
        # legacy path, -1 = not measured. The invariant a fused dispatch
        # must keep: exactly ONE fetch and ZERO translation
        # (fused_invariant_ok; violations counted by the perf window).
        self.fused = False
        self.fetches = 0
        self.translate_ms = -1.0

    # -- analytic totals -----------------------------------------------------

    def flops(self) -> int:
        """Useful distance FLOPs for the whole dispatch (actual rows)."""
        return int(round(2.0 * self.batch * self.n * self.dim))

    def bytes(self) -> int:
        """Store bytes read from HBM for the whole dispatch. On the
        pq_adc4 tier `bytes_per_row` covers only the stage-1 nibble scan
        (M/2 per scanned row); the re-rank stages gather per QUERY, not
        per row, so their traffic rides ``extra`` — funnel_c x the 8-bit
        code row for stage 2, funnel_rescore x the bf16 row for stage 3
        — and is added here per batch row."""
        total = self.n * self.bytes_per_row
        if self.extra and self.tier == TIER_PQ_ADC4:
            total += self.batch * (
                self.extra.get("funnel_c", 0)
                * self.extra.get("funnel_stage2_bytes_per_row", 0)
                + self.extra.get("funnel_rescore", 0)
                * self.extra.get("funnel_stage3_bytes_per_row", 0))
        return int(round(total))

    def hop_ms(self) -> float:
        """The host hop between the device fetch and hydration — finalize
        wall minus the blocking fetch. REDEFINED by the fused dispatch:
        on the legacy path this is unpack + the host slot->doc gather
        (the gather/rescore hop the r05 profile flagged); on a fused
        dispatch the translation runs ON DEVICE inside the same program,
        so the hop is dtype views + two word copies and its share of
        accounted wall collapses toward zero (docs/performance.md
        "anatomy of a fused dispatch"). -1 when the split was not
        measured."""
        if self.finalize_ms < 0.0 or self.device_ms < 0.0:
            return -1.0
        return max(self.finalize_ms - self.device_ms, 0.0)

    def ledger(self) -> dict:
        """{phase: ms} of every measured host-overhead ledger stage."""
        out = {}
        if self.filter_ms >= 0.0:
            out["filter"] = self.filter_ms
        if self.enqueue_ms >= 0.0:
            out["enqueue"] = self.enqueue_ms
        if self.device_ms >= 0.0:
            out["device"] = self.device_ms
        hop = self.hop_ms()
        if hop >= 0.0:
            out["gather_hop"] = hop
        if self.hydrate_ms >= 0.0:
            out["hydrate"] = self.hydrate_ms
        return out

    def describe(self) -> dict:
        """Flat dict of the analytic shape (bench rows, trace facts)."""
        d = {"tier": self.tier, "n": self.n, "dim": round(self.dim, 2),
             "batch": self.batch, "batch_padded": self.batch_padded,
             "k": self.k, "flops": self.flops(), "bytes": self.bytes(),
             "fused": self.fused}
        if self.ndev != 1:
            d["ndev"] = self.ndev
        if self.extra:
            d.update(self.extra)
        return d

    def roofline_at_qps(self, qps: float, backend: str = "tpu-v5e") -> dict:
        """Offline-style roofline for this shape at a measured QPS (bench
        rows: QPS is per query row, batches/s = qps/batch)."""
        return roofline_from_qps(qps, self.n, self.dim, self.batch,
                                 self.bytes_per_row, backend)

    def roofline(self, seconds: float, backend: Optional[str] = None) -> dict:
        """Per-dispatch roofline: this shape's work over `seconds` of
        device time."""
        return roofline(self.flops(), self.bytes(), seconds, backend)


def fused_invariant_ok(shape: "DispatchShape") -> bool:
    """The fused-dispatch ledger invariant: a dispatch that claims device-
    side translation must have made exactly ONE blocking fetch and spent
    ZERO measured host-translation time. Non-fused dispatches trivially
    pass (they make no claim). The perf window counts violations per
    window (monitoring/perf.py), and tests/test_fused_dispatch.py pins
    the contract per tier."""
    if not shape.fused:
        return True
    if shape.translate_ms != 0.0:
        return False
    if shape.n <= 0:
        # empty-gather early return: no device work ran, no fetch owed
        return shape.fetches <= 1
    return shape.fetches == 1


# -- roofline math ------------------------------------------------------------

def ridge(backend: Optional[str] = None) -> float:
    """The roofline ridge point (flops/byte) of a backend's peaks — the
    ONE place the compute-vs-bandwidth-bound threshold is computed."""
    peak = PEAKS.get(backend or detect_backend(), PEAKS["cpu"])
    return peak["tflops"] * 1e12 / (peak["hbm_gbs"] * 1e9)


def regime(flops: float, bytes_: float,
           backend: Optional[str] = None) -> str:
    """Which peak the work's arithmetic intensity pins."""
    ai = flops / max(bytes_, 1.0)
    return "compute-bound" if ai >= ridge(backend) else "hbm-bandwidth-bound"


def roofline(flops: float, bytes_: float, seconds: float,
             backend: Optional[str] = None) -> dict:
    """Achieved-vs-peak roofline for `flops`/`bytes_` of work done in
    `seconds`: the per-dispatch / per-window form (bench's QPS form wraps
    this). backend=None detects the live platform."""
    backend = backend or detect_backend()
    peak = PEAKS.get(backend, PEAKS["cpu"])
    secs = max(float(seconds), 1e-9)
    tflops = flops / secs / 1e12
    gbs = bytes_ / secs / 1e9
    ai = flops / max(bytes_, 1.0)
    return {
        "tflops": round(tflops, 3),
        "hbm_gbs": round(gbs, 2),
        "mfu_pct": round(100.0 * tflops / peak["tflops"], 2),
        "bw_pct": round(100.0 * gbs / peak["hbm_gbs"], 2),
        "arith_intensity_flops_per_byte": round(ai, 1),
        "ridge_flops_per_byte": round(ridge(backend), 1),
        "regime": regime(flops, bytes_, backend),
        "peaks": peak["note"],
    }


def roofline_from_qps(qps, n, dim, batch, bytes_per_row,
                      backend="tpu-v5e") -> dict:
    """Achieved-vs-peak roofline fields for one flat-scan row at a
    measured QPS (the bench.py form — field-for-field what bench's old
    ``_roofline`` emitted; tests/test_bench_roofline.py pins the math).

    FLOPs are the *useful* distance math (2·B·N·D per batch), bytes the
    store bytes read per batch; arithmetic intensity 2·B/bytes_per_elem —
    batch size decides the regime (the lever batch-first serving
    exploits)."""
    flops_per_batch = 2.0 * batch * n * dim
    bytes_per_batch = float(n) * bytes_per_row
    batches_per_s = qps / batch
    return roofline(flops_per_batch * batches_per_s,
                    bytes_per_batch * batches_per_s, 1.0, backend)


# -- exact attribution split --------------------------------------------------

def split_exact(total: int, rows: list, rows_total: int) -> list:
    """Split an integer `total` (flops/bytes) across riders proportionally
    to their `rows`, such that the parts SUM BIT-EXACTLY to the covered
    fraction: part_i = round(T·c_i/R) - round(T·c_{i-1}/R) over cumulative
    rows c — a telescoping sum, so when the riders cover all rows_total
    rows, sum(parts) == total with no float residue (the flops/bytes twin
    of the PR-3 device-time identity)."""
    total = int(total)
    rt = max(int(rows_total), 1)
    out = []
    cum = 0
    prev = 0
    for r in rows:
        cum += int(r)
        edge = (total * cum + rt // 2) // rt  # integer round-half-up
        out.append(edge - prev)
        prev = edge
    return out
