from weaviate_tpu.monitoring.metrics import Metrics, get_metrics, noop_metrics

__all__ = ["Metrics", "get_metrics", "noop_metrics"]
