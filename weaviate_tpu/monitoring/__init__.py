from weaviate_tpu.monitoring.metrics import (
    Metrics,
    get_metrics,
    noop_metrics,
    record_device_fallback,
)

__all__ = ["Metrics", "get_metrics", "noop_metrics", "record_device_fallback"]
