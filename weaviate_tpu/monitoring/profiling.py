"""Runtime profiling endpoints (the reference's pprof surface).

Reference: net/http/pprof is always mounted (adapters/handlers/rest/
configure_api.go:25) and setupGoProfiling (configure_api.go:679) turns on
block/mutex profiling from env flags. The Go runtime ships a sampling
profiler; Python does not — so the CPU profile here is a built-in wall-clock
stack sampler over `sys._current_frames()` (the same technique py-spy uses,
in-process): thread-aware, low overhead at the default 100 Hz, and needs no
instrumentation of the profiled code.

Endpoints (all GET, mounted on the main REST port like the reference):
  /debug/pprof/            index
  /debug/pprof/profile     sample all threads for ?seconds=N (default 5,
                           ?hz=100) -> collapsed-stack text (flamegraph
                           input format: "frame;frame;frame count")
  /debug/pprof/goroutine   one-shot dump of every live thread's stack
  /debug/pprof/heap        tracemalloc top allocation sites (?limit=30);
                           first call arms tracemalloc and reports that
  /debug/pprof/cmdline     process argv
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback


class StackSampler:
    """Wall-clock sampling profiler over sys._current_frames()."""

    def __init__(self):
        self._lock = threading.Lock()  # one profile run at a time

    def profile(self, seconds: float = 5.0, hz: int = 100) -> str:
        seconds = max(0.05, min(float(seconds), 30.0))
        hz = max(1, min(int(hz), 1000))
        interval = 1.0 / hz
        counts: dict[tuple, int] = {}
        own = threading.get_ident()
        if not self._lock.acquire(timeout=1.0):
            raise RuntimeError("another profile is already running")
        try:
            deadline = time.monotonic() + seconds
            while time.monotonic() < deadline:
                for tid, frame in sys._current_frames().items():
                    if tid == own:
                        continue
                    stack = []
                    f = frame
                    while f is not None and len(stack) < 64:
                        code = f.f_code
                        stack.append(f"{code.co_filename.rsplit('/', 1)[-1]}:{code.co_name}")
                        f = f.f_back
                    key = tuple(reversed(stack))
                    counts[key] = counts.get(key, 0) + 1
                time.sleep(interval)
        finally:
            self._lock.release()
        lines = [
            f"{';'.join(stack)} {n}"
            for stack, n in sorted(counts.items(), key=lambda kv: -kv[1])
        ]
        return "\n".join(lines) + ("\n" if lines else "")


def thread_dump() -> str:
    """All live threads with their current stacks (pprof /goroutine twin)."""
    names = {t.ident: t for t in threading.enumerate()}
    out = []
    for tid, frame in sorted(sys._current_frames().items()):
        t = names.get(tid)
        label = t.name if t else "?"
        daemon = " daemon" if t is not None and t.daemon else ""
        out.append(f"thread {tid} [{label}]{daemon}:")
        out.extend(line.rstrip("\n") for line in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out) + "\n"


def heap_profile(limit: int = 30) -> str:
    """tracemalloc top allocation sites; arms tracing on first call (the
    price of not paying tracemalloc overhead when nobody is profiling)."""
    import tracemalloc

    limit = max(1, min(int(limit), 200))
    if not tracemalloc.is_tracing():
        tracemalloc.start(16)
        return (
            "tracemalloc armed by this request; allocations are tracked "
            "from now on — call /debug/pprof/heap again after the workload\n"
        )
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")[:limit]
    total = sum(s.size for s in snap.statistics("filename"))
    out = [f"total tracked: {total / 1024:.1f} KiB; top {len(stats)} by line:"]
    for s in stats:
        out.append(f"  {s.size / 1024:10.1f} KiB  {s.count:8d} blocks  {s.traceback}")
    return "\n".join(out) + "\n"


def cmdline() -> str:
    return "\x00".join(sys.argv) + "\n"


_trace_lock = threading.Lock()


class TraceBusyError(RuntimeError):
    """A device trace is already being captured (maps to HTTP 409)."""


# -- signal/atexit-safe capture teardown --------------------------------------
#
# The r05 chip session wedged when a profiling process was killed
# mid-device-op: jax.profiler.start_trace without its stop_trace leaves the
# device-side profiling session armed, and the NEXT process to touch the
# chip inherits a wedged relay (BENCH_TPU_r05_manual.json note). The
# in-function try/finally already covers exceptions; this covers the exits
# that skip finally blocks — SIGTERM's default handler and interpreter
# teardown — by stopping any active capture from an atexit hook and a
# chaining SIGTERM handler.

_teardown_state = {"active": False, "atexit_installed": False,
                   "signal_installed": False, "prev_sigterm": None}
_teardown_lock = threading.Lock()

# teardown hooks run AFTER the capture stop and BEFORE any signal
# re-delivery: the incident flight recorder (monitoring/incidents.py)
# chains its dump here, so a process dying mid-serve leaves a measured
# post-mortem (stop capture -> dump bundle -> re-deliver). Each hook is
# exception-guarded — teardown must never raise.
_teardown_hooks: list = []


def register_teardown_hook(fn) -> None:
    """Add `fn` to the SIGTERM/atexit teardown chain (idempotent per
    function object). Hooks must be safe to call at any time — they run
    with the process dying."""
    with _teardown_lock:
        if fn not in _teardown_hooks:
            _teardown_hooks.append(fn)


def _run_teardown_hooks() -> None:
    with _teardown_lock:
        hooks = list(_teardown_hooks)
    for fn in hooks:
        try:
            fn()
        except Exception:  # noqa: BLE001 — teardown must never raise
            pass


def stop_active_trace() -> bool:
    """Stop the active device-trace capture if one is running. Idempotent
    and exception-proof — safe from atexit, a signal handler, or the
    capture's own finally. -> True when a capture was actually stopped."""
    with _teardown_lock:
        if not _teardown_state["active"]:
            return False
        _teardown_state["active"] = False
    try:
        import jax

        jax.profiler.stop_trace()
        return True
    except Exception:  # noqa: BLE001 — teardown must never raise
        return False


def _atexit_teardown() -> None:
    """Normal-exit half of the teardown: stop any active capture, then run
    the chained hooks (a cleanly shut-down App has already unconfigured
    its recorder, so its hook no-ops; an App still live at exit dumps)."""
    stop_active_trace()
    _run_teardown_hooks()


def _sigterm_teardown(signum, frame):
    # stop capture -> dump bundle -> re-deliver: the hooks (the incident
    # recorder's dump) run after the profiler stop so the bundle never
    # races an armed device capture, and before re-delivery so the
    # process's exit status is unchanged
    stop_active_trace()
    _run_teardown_hooks()
    prev = _teardown_state["prev_sigterm"]
    import signal as _signal

    if prev is _signal.SIG_IGN:
        # the process had deliberately ignored SIGTERM before the
        # teardown was installed — honor that: stop the capture, swallow
        # the signal (re-delivering would turn an ignored signal fatal)
        return
    if callable(prev):
        prev(signum, frame)
    else:
        # restore the default disposition and re-deliver, so the process
        # still dies with the SIGTERM exit status the supervisor expects
        _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
        os.kill(os.getpid(), signum)


def install_trace_teardown() -> bool:
    """Arm the atexit + SIGTERM teardown for device-trace captures. Called
    from App startup (likely the main thread — only the main thread may
    install signal handlers; elsewhere the atexit hook still arms and the
    call reports False for the signal half). Idempotent; the signal half
    latches only on SUCCESS, so a first call off the main thread does not
    forfeit a later main-thread install."""
    import atexit
    import signal as _signal

    with _teardown_lock:
        if _teardown_state["signal_installed"]:
            return True
        if not _teardown_state["atexit_installed"]:
            _teardown_state["atexit_installed"] = True
            atexit.register(_atexit_teardown)
    try:
        prev = _signal.getsignal(_signal.SIGTERM)
        if prev is _sigterm_teardown:  # foreign reinstall of our handler
            prev = None
        _signal.signal(_signal.SIGTERM, _sigterm_teardown)
        with _teardown_lock:
            _teardown_state["prev_sigterm"] = prev
            _teardown_state["signal_installed"] = True
        return True
    except (ValueError, OSError):
        # not the main thread (a REST handler racing App init) — atexit
        # still protects normal exits; a later main-thread call retries
        return False


def device_trace(data_path: str, seconds: float = 3.0) -> str:
    """Capture a JAX device trace for ?seconds — the TPU twin of pprof's
    execution trace (the reference's /debug/pprof/trace). Records XLA op
    timelines and device (TPU/HBM) activity for whatever the serving path
    runs during the window; writes a perfetto/tensorboard trace under
    <data>/traces/<stamp>/ and returns its path + file listing (view with
    `tensorboard --logdir` or ui.perfetto.dev). One capture at a time —
    concurrent requests get an explicit error, not a corrupt trace."""
    import glob
    import tempfile

    import jax

    if not _trace_lock.acquire(blocking=False):
        raise TraceBusyError("a device trace is already being captured")
    try:
        root = os.path.join(data_path, "traces")
        os.makedirs(root, exist_ok=True)
        # mkdtemp: consecutive captures in the same wall-clock second must
        # not merge into one tensorboard/perfetto session
        out_dir = tempfile.mkdtemp(
            prefix=time.strftime("%Y%m%d-%H%M%S-"), dir=root)
        # arm the emergency teardown BEFORE starting: a SIGTERM landing
        # between start_trace and the finally must still stop the capture
        # (atexit for normal exits; the chaining SIGTERM handler when one
        # could be installed — see install_trace_teardown)
        install_trace_teardown()
        with _teardown_lock:
            _teardown_state["active"] = True
        jax.profiler.start_trace(out_dir)
        try:
            time.sleep(max(0.0, min(float(seconds), 60.0)))
        finally:
            stop_active_trace()
        files = sorted(
            os.path.relpath(p, out_dir)
            for p in glob.glob(os.path.join(out_dir, "**"), recursive=True)
            if os.path.isfile(p))
        return (f"device trace written to {out_dir}\n"
                + "".join(f"  {f}\n" for f in files)
                + "view: tensorboard --logdir <dir>  (or ui.perfetto.dev)\n")
    finally:
        _trace_lock.release()


def index() -> str:
    return (
        "/debug/pprof/\n"
        "  profile?seconds=5&hz=100  sampled CPU profile (collapsed stacks)\n"
        "  trace?seconds=3           JAX device trace (XLA ops, TPU activity)\n"
        "  goroutine                 all thread stacks\n"
        "  heap?limit=30             tracemalloc top allocation sites\n"
        "  cmdline                   process argv\n"
    )
