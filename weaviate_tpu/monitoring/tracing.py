"""Request tracing with device-time attribution across the coalesced path.

The existing observability surface — the per-phase histograms of
shard_read.go parity (filter / device_search / hydrate) and the pprof
mount — aggregates across requests. The cross-request query coalescer
(serving/coalescer.py) broke the implicit 1:1 mapping between a request and
its device work: ~21 requests share one padded dispatch, so no histogram
can answer "where did THIS slow query spend its time" or "how much
padding / queue wait did tenant X pay". This module restores per-request
answers with a low-overhead span tracer:

  - handlers (REST / GraphQL / gRPC) accept and emit W3C ``traceparent``
    (``X-Request-Id`` fallback) and open a sampled request trace;
  - the active span travels in a ``contextvars.ContextVar`` through
    usecases/traverser.py into serving/coalescer.py lanes, and across the
    coalescer's flush-thread / dispatch-pool handoffs as explicit captures
    (a ``_Waiter`` carries its submitter's span; the dispatch record rides
    a second ContextVar set around the shard call);
  - each shard dispatch (db/shard.py, index/tpu.py) records device-phase
    timings (filter, device_search, rescore, hydrate — rescore is fused
    into device_search on this implementation: upload+scan+rescore+topk
    are one XLA program) plus dispatch facts: padded-vs-actual rows, the
    first-sighting-of-this-jit-shape bit, lane queue wait, occupancy.

Fan-in/fan-out attribution — the key design problem — happens in
``DispatchRecord.finish()``: ONE coalesced dispatch splits its device time
back across every rider request's trace proportionally by rows
(``share = rows_i / actual_rows``), so the riders' attributed device times
sum exactly to the dispatch's device span (padding overhead is reported
separately as ``padding_waste``, never smeared into shares). Attribution
creates already-closed spans atomically, and every open span closes in a
``finally`` (handler roots) — bypass, error, and shutdown paths annotate
the rider traces instead of leaking spans.

Exposure (all bounded):
  - a fixed-size ring buffer of completed traces, served as JSON at
    ``GET /debug/traces`` behind the same authorizer as pprof;
  - a structured slow-query log: one JSON line (full span tree) on the
    ``weaviate_tpu.slowquery`` logger when a trace exceeds
    ``SLOW_QUERY_THRESHOLD_MS``;
  - exemplar counters in the existing ``Metrics`` registry
    (``weaviate_traces_total``, ``weaviate_trace_phase_ms``,
    ``weaviate_trace_dispatch_rows_total``), observation exception-guarded
    like every other serving-path metric.

Disabled (``TRACING_ENABLED`` unset) the module global ``_tracer`` is
``None`` and every entry point returns after that one comparison: no span
objects, no ContextVar writes, no locks — the serving hot path makes zero
tracing calls (pinned by a spy test in tests/test_tracing.py). Enabled,
the cost is O(spans) per sampled request with no locks on the dispatch
hot path (phase recording appends to a plain list owned by one thread;
the only locks are per-trace child-append and the ring append at finish).
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import logging
import random
import re
import threading
import time
import uuid
from collections import deque
from typing import Any, Iterator, Optional

from weaviate_tpu.monitoring import costmodel

_SLOW_LOG = logging.getLogger("weaviate_tpu.slowquery")

# one traceparent shape only: version 00, 32-hex trace id, 16-hex parent id
_TRACEPARENT_RE = re.compile(
    r"^\s*00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})\s*$")

# monotonically increasing dispatch ids: lets a reader of /debug/traces (or
# the attribution-identity test) regroup rider spans of one device dispatch
_dispatch_seq = itertools.count(1)


def parse_traceparent(value: Optional[str]) -> Optional[tuple[str, str, str]]:
    """W3C traceparent -> (trace_id, parent_span_id, flags), or None."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value)
    if m is None:
        return None
    if m.group(1) == "0" * 32 or m.group(2) == "0" * 16:
        return None  # the spec's invalid all-zero ids
    return m.group(1), m.group(2), m.group(3)


def gen_request_id() -> str:
    """Request id for responses — independent of tracing enablement (the
    X-Request-Id contract holds even with the tracer off)."""
    return uuid.uuid4().hex


_RID_BAD = re.compile(r"[^\x21-\x7e]")


def clean_request_id(value: Optional[str]) -> str:
    """Inbound request id made safe to ECHO into a response header /
    trailing metadata: printable ASCII only (a CR/LF smuggled through an
    obs-folded header must not become header injection), bounded length;
    empty after cleaning => a generated id."""
    rid = _RID_BAD.sub("", (value or "").strip())[:128]
    return rid or gen_request_id()


class Span:
    """One timed node in a request's trace tree. Children may be appended
    from other threads (coalesced-dispatch attribution), so the append goes
    through the owning trace's lock; everything else is single-writer."""

    __slots__ = ("name", "trace", "attrs", "children", "duration_ms", "_t0")

    def __init__(self, name: str, trace: "Trace",
                 attrs: Optional[dict] = None,
                 duration_ms: Optional[float] = None):
        self.name = name
        self.trace = trace
        self.attrs: dict[str, Any] = dict(attrs) if attrs else {}
        self.children: list[Span] = []
        self.duration_ms = duration_ms
        self._t0 = time.perf_counter() if duration_ms is None else None

    def end(self) -> None:
        if self.duration_ms is None and self._t0 is not None:
            self.duration_ms = (time.perf_counter() - self._t0) * 1000.0

    def child_start(self, name: str, attrs: Optional[dict] = None) -> "Span":
        """Open a child span (the caller owns closing it — prefer the
        ``span()`` context manager, which can't leak)."""
        c = Span(name, self.trace, attrs)
        with self.trace.lock:
            self.children.append(c)
        return c

    def child_done(self, name: str, duration_ms: float,
                   attrs: Optional[dict] = None) -> "Span":
        """Attach an already-closed child (post-hoc attribution): created
        and finished atomically, so attribution can never leak an open
        span on an error path."""
        c = Span(name, self.trace, attrs, duration_ms=float(duration_ms))
        with self.trace.lock:
            self.children.append(c)
        return c

    def annotate(self, key: str, value: Any) -> None:
        with self.trace.lock:
            self.attrs[key] = value

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"name": self.name}
        if self.duration_ms is not None:
            d["duration_ms"] = round(self.duration_ms, 3)
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


class Trace:
    """One sampled request: ids + the root span + a lock guarding
    cross-thread attachment (dispatch-pool attribution)."""

    __slots__ = ("trace_id", "span_id", "parent_span_id", "request_id",
                 "kind", "name", "root", "lock", "start_unix_ms")

    def __init__(self, kind: str, name: str, trace_id: str,
                 parent_span_id: Optional[str], request_id: str,
                 attrs: Optional[dict] = None):
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_span_id = parent_span_id
        self.request_id = request_id
        self.kind = kind
        self.name = name
        self.lock = threading.Lock()
        self.start_unix_ms = time.time() * 1000.0
        self.root = Span("request", self, attrs)

    def traceparent(self) -> str:
        """The outbound W3C header value for this trace's root."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "request_id": self.request_id,
            "kind": self.kind,
            "name": self.name,
            "start_unix_ms": round(self.start_unix_ms, 1),
            "duration_ms": (round(self.root.duration_ms, 3)
                            if self.root.duration_ms is not None else None),
            "root": self.root.to_dict(),
        }


class DispatchRecord:
    """Phase/fact accumulator for ONE device dispatch, attributed at
    ``finish()`` across every rider request's trace.

    riders: ``[(span, rows, queue_wait_ms)]`` — the span each rider's
    attribution attaches under (captured on the submitting thread), its row
    count, and its admission-queue wait. ``owned=True`` means the creator
    (the shard, on the direct path) must call finish(); the coalescer
    creates unowned records and finishes them after the device work, before
    waking the waiters, so attribution is complete when a request thread
    reads its own trace.

    Attribution math: ``share_i = rows_i / actual_rows``; every phase (and
    the dispatch total) is split by share, so when all riders are sampled
    ``sum_i(device_ms_i) == dispatch device_ms`` exactly (float error
    aside) — the identity tests/test_tracing.py pins. Padding overhead is
    NOT smeared into shares: it is reported as ``padding_waste =
    1 - actual_rows/padded_rows`` so "how much padding did this request
    pay" stays answerable separately.
    """

    __slots__ = ("riders", "owned", "attrs", "phases", "ledger_entries",
                 "_finished")

    def __init__(self, riders: list[tuple[Span, int, float]],
                 owned: bool = True, **attrs):
        self.riders = riders
        self.owned = owned
        self.attrs: dict[str, Any] = {"dispatch_id": next(_dispatch_seq)}
        self.attrs.update(attrs)
        self.phases: list[tuple[str, float]] = []
        # host-overhead ledger (monitoring/perf.py stages): finer than the
        # attribution phases — enqueue / device fetch / gather hop — and
        # kept SEPARATE from `phases` so the attribution identity (rider
        # phase shares sum to the dispatch span) is untouched by ledger
        # stages that overlap the device_search interval
        self.ledger_entries: list[tuple[str, float]] = []
        self._finished = False

    def phase(self, name: str, ms: float) -> None:
        """Record one device-phase duration (filter, device_search, rescore,
        hydrate). Single-threaded by construction (the dispatching thread),
        so no lock on the hot path."""
        self.phases.append((name, float(ms)))

    def fact(self, **kw) -> None:
        self.attrs.update(kw)

    def attach_shape(self, shape) -> None:
        """Fold a costmodel.DispatchShape's analytic facts + host-overhead
        ledger into this record (db/shard.py calls it right after the
        dispatch's phases land, before finish()). The roofline facts
        themselves are computed at finish()."""
        self.attrs.update(tier=shape.tier, n_live=shape.n,
                          dim=shape.dim, flops=shape.flops(),
                          bytes=shape.bytes())
        if shape.t_end > shape.t_start:
            # the dispatch's enqueue->fetch wall: the per-dispatch roofline
            # denominator. The blocked-fetch time is only a LOWER bound on
            # device time (a result that landed while the host was doing
            # enqueue/compile work fetches in ~0 ms), so dividing by it
            # can fabricate >100% MFU; the wall form is an honest
            # serving-level number (kernel-level lives in /debug/perf's
            # device-busy aggregate)
            self.attrs["dispatch_wall_ms"] = round(
                (shape.t_end - shape.t_start) * 1000.0, 3)
        for name, ms in shape.ledger().items():
            self.ledger_entries.append((name, ms))

    def finish(self) -> None:
        """Split this dispatch across its riders' traces. Idempotent, and
        every span it creates is born closed — no error path can leak."""
        if self._finished:
            return
        self._finished = True
        total_ms = sum(ms for _, ms in self.phases)
        device_ms = sum(ms for n, ms in self.phases if n == "device_search")
        rows_total = int(self.attrs.get("actual_rows") or 0) \
            or sum(r for _, r, _ in self.riders) or 1
        padded = int(self.attrs.get("padded_rows") or 0)
        if padded > 0:
            self.attrs["padding_waste"] = round(
                max(0.0, 1.0 - rows_total / padded), 4)
        # roofline facts (costmodel): the dispatch's analytic work over its
        # enqueue->fetch WALL — the serving-level per-dispatch utilization.
        # Deliberately NOT over the blocked-fetch time: that is a lower
        # bound on device time (a dispatch overlapping host work fetches
        # in ~0 ms and would read as >100% MFU); kernel-level utilization
        # comes from /debug/perf's device-busy aggregate instead.
        flops = self.attrs.get("flops")
        ledger = dict(self.ledger_entries)
        if flops:
            dev_ms = self.attrs.get("dispatch_wall_ms") or device_ms
            if dev_ms > 0.0:
                rf = costmodel.roofline(
                    flops, self.attrs.get("bytes", 0), dev_ms / 1000.0)
                self.attrs.update(
                    mfu_pct=rf["mfu_pct"], hbm_bw_pct=rf["bw_pct"],
                    arith_intensity=rf["arith_intensity_flops_per_byte"],
                    regime=rf["regime"])
        if ledger:
            self.attrs["ledger_ms"] = {
                k: round(v, 3) for k, v in ledger.items()}
        # per-rider flops/bytes: telescoping integer split, so when every
        # rider is sampled the parts sum BIT-EXACTLY to the dispatch
        # totals (the flops/bytes twin of the device-time identity)
        rider_rows = [r for _, r, _ in self.riders]
        rider_flops = (costmodel.split_exact(flops, rider_rows, rows_total)
                       if flops else None)
        rider_bytes = (costmodel.split_exact(
            self.attrs.get("bytes", 0), rider_rows, rows_total)
            if flops else None)
        t = _tracer
        m = t.metrics if t is not None else None
        for i, (span, rows, wait_ms) in enumerate(self.riders):
            share = rows / rows_total
            attrs = {
                **self.attrs,
                "rows": rows,
                "share": round(share, 6),
                "queue_wait_ms": round(wait_ms, 3),
                "device_ms": device_ms * share,
                "dispatch_device_ms": device_ms,
                "dispatch_total_ms": total_ms,
            }
            if rider_flops is not None:
                attrs["flops"] = rider_flops[i]
                attrs["bytes"] = rider_bytes[i]
                attrs["dispatch_flops"] = flops
                attrs["dispatch_bytes"] = self.attrs.get("bytes", 0)
            d = span.child_done("dispatch", duration_ms=total_ms * share,
                                attrs=attrs)
            for nm, ms in self.phases:
                d.child_done(nm, duration_ms=ms * share)
            if m is not None:
                try:
                    if wait_ms > 0.0:
                        m.trace_phase.labels("queue_wait").observe(wait_ms)
                    for nm, ms in self.phases:
                        m.trace_phase.labels(nm).observe(ms * share)
                except Exception:  # noqa: BLE001 — metrics must not break serving
                    pass
        if m is not None:
            try:
                m.trace_dispatch_rows.labels("actual").inc(rows_total)
                if padded:
                    m.trace_dispatch_rows.labels("padded").inc(padded)
            except Exception:  # noqa: BLE001 — metrics must not break serving
                pass


class Tracer:
    """Process-wide trace collector: sampling decision, completed-trace
    ring buffer, slow-query log, exemplar metrics, and the seen-jit-shape
    set behind the compile-vs-cache-hit dispatch fact."""

    def __init__(self, sample_rate: float = 1.0, ring_size: int = 256,
                 slow_ms: float = 1000.0, metrics=None):
        self.sample_rate = min(max(float(sample_rate), 0.0), 1.0)
        self.slow_ms = float(slow_ms)
        self.metrics = metrics
        self._ring: deque = deque(maxlen=max(int(ring_size), 1))
        self._ring_lock = threading.Lock()
        # (id(index), padded_rows, k) shapes seen since tracing began: the
        # first dispatch of a shape is (a proxy for) the jit compile. Bounded
        # so a pathological shape churn cannot grow it without limit.
        self._shapes: set = set()
        self._shapes_lock = threading.Lock()

    def set_sample_rate(self, rate: float) -> None:
        """Adjust the trace sampling gate (clamped to [0, 1]). The
        control plane's brownout stage 3 pauses sampling with 0 and
        restores the configured rate on recovery/revert; /debug/perf
        coverage is unaffected (the shard feeds every dispatch while the
        tracer is up, independent of sampling). serving/controller.py is
        the only caller outside tests (graftlint JGL014)."""
        self.sample_rate = min(max(float(rate), 0.0), 1.0)

    # -- request lifecycle ---------------------------------------------------

    def start_request(self, kind: str, name: str,
                      traceparent: Optional[str] = None,
                      request_id: Optional[str] = None,
                      attrs: Optional[dict] = None) -> Optional[Trace]:
        """-> a sampled Trace, or None (sampled out; counted)."""
        if self.sample_rate < 1.0 and random.random() >= self.sample_rate:
            m = self.metrics
            if m is not None:
                try:
                    m.traces.labels(kind, "unsampled").inc()
                except Exception:  # noqa: BLE001
                    pass
            return None
        parsed = parse_traceparent(traceparent)
        if parsed is not None:
            trace_id, parent_span_id, _flags = parsed
        else:
            trace_id, parent_span_id = uuid.uuid4().hex, None
        return Trace(kind, name, trace_id, parent_span_id,
                     request_id or gen_request_id(), attrs)

    def finish(self, trace: Trace, error: Optional[BaseException] = None) -> None:
        """Close the root span, push the trace to the ring, slow-log and
        count it. Exactly once per trace (the request() context manager's
        finally owns the call)."""
        if error is not None:
            trace.root.attrs["error"] = f"{type(error).__name__}: {error}"
        trace.root.end()
        doc = trace.to_dict()
        with self._ring_lock:
            self._ring.append(doc)
        dur = trace.root.duration_ms or 0.0
        slow = self.slow_ms > 0.0 and dur >= self.slow_ms
        if slow:
            try:
                _SLOW_LOG.warning("%s", json.dumps(
                    {"slow_query": True, "threshold_ms": self.slow_ms, **doc},
                    default=str))
            except Exception:  # noqa: BLE001 — logging must not break serving
                pass
        m = self.metrics
        if m is not None:
            try:
                outcome = ("error" if error is not None
                           else "slow" if slow else "ok")
                m.traces.labels(trace.kind, outcome).inc()
            except Exception:  # noqa: BLE001
                pass

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> list[dict]:
        """Completed traces, oldest first (the /debug/traces body)."""
        with self._ring_lock:
            return list(self._ring)

    def clear(self) -> None:
        """Drop buffered traces (bench windows reset between measurements)."""
        with self._ring_lock:
            self._ring.clear()

    def first_shape(self, key: tuple) -> bool:
        """True the first time a dispatch shape is seen since tracing began
        — a proxy for "this dispatch paid the jit compile" (shapes warmed
        before the tracer came up read as first sightings once)."""
        with self._shapes_lock:
            if key in self._shapes:
                return False
            if len(self._shapes) >= 8192:  # runaway shape churn backstop
                self._shapes.clear()
            self._shapes.add(key)
        # a first sighting is (a proxy for) a jit compile — journal it so
        # an incident bundle shows whether the window around a latency
        # spike was paying compiles (monitoring/incidents.py; burst-
        # coalesced, one-comparison no-op when the plane is off). Lazy
        # import: incidents is off tracing's import path by design.
        try:
            from weaviate_tpu.monitoring import incidents

            incidents.emit("jit_compile", scope="dispatch",
                           padded_rows=int(key[1]), k=int(key[2]))
        except Exception:  # noqa: BLE001 — observability must not break serving
            pass
        return True


# -- module state + zero-hop accessors ----------------------------------------

_tracer: Optional[Tracer] = None

# the active span of the current request (serving thread + anything
# contextvars copies into); None when disabled, unsampled, or off-request
_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "weaviate_trace_span", default=None)
# the coalescer-owned dispatch record, set around the shard call on the
# flush/dispatch-pool threads so shard phase recording lands in the record
# that knows the lane's riders
_DISPATCH = contextvars.ContextVar("weaviate_trace_dispatch", default=None)


def configure(tracer: Optional[Tracer]) -> Optional[Tracer]:
    """Install (or clear, with None) the process-wide tracer."""
    global _tracer
    _tracer = tracer
    return tracer


def unconfigure(tracer: Tracer) -> None:
    """Clear the global only if it is still `tracer` (App shutdown must not
    tear down a newer App's tracer)."""
    global _tracer
    if _tracer is tracer:
        _tracer = None


def get_tracer() -> Optional[Tracer]:
    return _tracer


def current_span() -> Optional[Span]:
    """The active span, or None. First check is the disabled fast path."""
    if _tracer is None:
        return None
    return _CURRENT.get()


@contextlib.contextmanager
def request(kind: str, name: str, traceparent: Optional[str] = None,
            request_id: Optional[str] = None, **attrs) -> Iterator[Optional[Trace]]:
    """Root context manager for one request: sampling, contextvar install,
    guaranteed finish (error recorded) in finally."""
    t = _tracer
    if t is None:
        yield None
        return
    tr = t.start_request(kind, name, traceparent=traceparent,
                         request_id=request_id, attrs=attrs or None)
    if tr is None:
        yield None
        return
    token = _CURRENT.set(tr.root)
    err: Optional[BaseException] = None
    try:
        yield tr
    except BaseException as e:
        err = e
        raise
    finally:
        _CURRENT.reset(token)
        t.finish(tr, error=err)


@contextlib.contextmanager
def span(name: str, **attrs) -> Iterator[Optional[Span]]:
    """Child span under the current one; no-op (yields None) when there is
    no active trace. Closing is structural — this is the API the JGL007
    graftlint rule steers serving/db code toward."""
    parent = current_span()
    if parent is None:
        yield None
        return
    s = parent.child_start(name, attrs or None)
    token = _CURRENT.set(s)
    try:
        yield s
    except BaseException as e:
        s.attrs["error"] = f"{type(e).__name__}: {e}"
        raise
    finally:
        _CURRENT.reset(token)
        s.end()


def dispatch_record(actual_rows: int = 0) -> Optional[DispatchRecord]:
    """The record a shard dispatch should record phases into:

    - the coalescer-installed record (its lifecycle is the coalescer's:
      ``owned`` False), when one is set for this thread;
    - else a fresh single-rider record bound to the current request span
      (direct path; ``owned`` True — the caller must finish() in a
      ``finally``);
    - else None (disabled / unsampled / off-request): the zero-hop path.
    """
    if _tracer is None:
        return None
    rec = _DISPATCH.get()
    if rec is not None:
        return rec
    s = _CURRENT.get()
    if s is None:
        return None
    rows = max(int(actual_rows), 1)
    return DispatchRecord([(s, rows, 0.0)], owned=True, actual_rows=rows)


def push_dispatch(rec: Optional[DispatchRecord]):
    """Install `rec` for this thread (coalescer, around the shard call).
    -> token for pop_dispatch; None rec => None token, both no-ops."""
    if rec is None:
        return None
    return _DISPATCH.set(rec)


def pop_dispatch(token) -> None:
    if token is not None:
        _DISPATCH.reset(token)


def note_shape(key: tuple) -> Optional[bool]:
    """First-sighting bit for a dispatch jit shape; None when disabled."""
    t = _tracer
    if t is None:
        return None
    return t.first_shape(key)


def annotate_current(key: str, value: Any) -> None:
    """Set an attribute on the current request's active span (bypass
    reasons, retry markers). No-op off-trace."""
    s = current_span()
    if s is not None:
        s.annotate(key, value)


def annotate_span(s: Optional[Span], key: str, value: Any) -> None:
    """Set an attribute on a captured span from another thread (the
    coalescer's error/shutdown paths annotating rider traces)."""
    if _tracer is None or s is None:
        return
    s.annotate(key, value)
