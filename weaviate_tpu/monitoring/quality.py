"""Online quality observability: the shadow recall auditor.

ROADMAP items 1-3 (fused multi-stage search, mesh serving, IVF pruning)
all trade recall for speed via tunable candidate budgets, yet recall is
only measured at bench time against a static fixture — live traffic has
zero quality signal, so a PQ-tier regression, tombstone accumulation
after deletes, or a too-aggressive budget would ship silently. This
module is the quality twin of the /debug/perf roofline ledger
(monitoring/perf.py): a continuous, production-path recall meter.

How it works:

- the shard captures a sampled fraction of completed live searches at
  finalize (``RECALL_AUDIT_SAMPLE_RATE``; default 0 = off) — the query
  rows, requested k, allowList, and the returned (ids, dists);
- the index pins the exact ``IndexSnapshot`` the dispatch read (the
  ``pop_read_lock_wait`` TLS idiom, gated on ``get_auditor()`` so the
  disabled path stores nothing), so the audit compares against the SAME
  index state the live answer saw — deletes/compression between capture
  and audit cannot fabricate a recall drop;
- a bounded background worker re-executes each sampled query against the
  exact host plane (``search_by_vectors_host_pinned`` — the breaker's
  brute-force fallback, which is exact by construction, filters and both
  PQ tiers included) and scores the live answer: recall@k, rank-biased
  overlap, and relative distance error, folded into a rolling
  ``QualityWindow`` (the ``PerfWindow`` idiom);
- per-tier EWMA degradation detection fires a rate-limited log plus
  ``weaviate_quality_degraded_total`` when the recall estimate drops
  below ``RECALL_ALERT_THRESHOLD``.

Subordination guarantees — audits must never compete with live traffic:

- hard concurrency budget (``RECALL_AUDIT_CONCURRENCY`` worker threads)
  with a tiny drop-not-queue backlog: when the queue is full the sample
  is DROPPED and counted (``weaviate_quality_audits_total{outcome=
  "shed"}``), never queued unboundedly;
- per-audit row budget (``RECALL_AUDIT_MAX_ROWS``): a wide coalesced
  dispatch audits a uniform row subset, not the whole batch;
- deadline-bounded host scans (``RECALL_AUDIT_DEADLINE_MS``): the host
  brute force streams row chunks and abandons the audit when over
  budget (counted as ``outcome="deadline"``);
- zero interaction with the coalescer, breaker, or tenant budgets: the
  audit calls the index's host plane directly, off every serving gate.

Lifecycle mirrors the tracer/perf window: a process-wide module global
installed by App when the sample rate is positive, None otherwise —
every serving-path entry point is then a one-comparison no-op and
constructs nothing (spy-pinned in tests/test_quality_auditor.py).

Exposure: ``GET /debug/quality`` (same authorizer as pprof/perf),
bounded-label gauges ``weaviate_recall_at_k{tier}`` /
``weaviate_distance_relerr{tier}``, audit outcome/lag counters, and the
``online_recall`` field on bench.py serving rows (cross-checked against
the bench's own recall computation). See docs/quality.md.
"""

from __future__ import annotations

import logging
import queue
import random
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from weaviate_tpu.testing import sanitizers

_LOG = logging.getLogger(__name__)

# RBO persistence: weight of deeper ranks (0.9 = the literature's default
# "top-heavy but not myopic" setting); truncated at k and normalized so
# identical rankings score exactly 1.0
RBO_P = 0.9

# seconds between degradation log lines per tier (the counter always
# increments on a transition; the log is what gets rate-limited)
DEGRADED_LOG_INTERVAL_S = 60.0


class AuditDeadlineExceeded(Exception):
    """A deadline-bounded host scan ran over its audit budget."""


# -- result scoring -----------------------------------------------------------


def recall_at_k(live_ids, host_ids, k: int) -> float:
    """|live top-k ∩ exact top-k| / |exact top-k| for ONE query row.
    host_ids is the ground truth; an empty ground truth scores 1.0 (there
    was nothing to miss)."""
    want = set(int(x) for x in host_ids[:k])
    if not want:
        return 1.0
    got = set(int(x) for x in live_ids[:k])
    return len(want & got) / len(want)


def rank_biased_overlap(live_ids, host_ids, k: int, p: float = RBO_P) -> float:
    """Truncated rank-biased overlap at depth k, normalized so identical
    rankings score 1.0: RBO@k = (1-p)/(1-p^k) · Σ_{d=1..k} p^{d-1}·A_d
    with A_d the overlap fraction of the two depth-d prefixes. Unlike
    recall it penalizes ORDER swaps, so a tier that returns the right set
    in the wrong order is still visible."""
    a = [int(x) for x in live_ids[:k]]
    b = [int(x) for x in host_ids[:k]]
    depth = max(len(a), len(b))
    if depth == 0:
        return 1.0
    sa: set = set()
    sb: set = set()
    acc = 0.0
    weight = 1.0  # p^(d-1)
    norm = 0.0
    for d in range(1, depth + 1):
        if d <= len(a):
            sa.add(a[d - 1])
        if d <= len(b):
            sb.add(b[d - 1])
        acc += weight * (len(sa & sb) / d)
        norm += weight
        weight *= p
    return acc / norm if norm > 0.0 else 1.0


def relative_distance_error(live_d, host_d) -> float:
    """Mean rank-aligned |d_live - d_exact| / max(|d_exact|, eps) over the
    ranks both lists filled — the tier's distance-approximation error,
    independent of whether the ids matched (a PQ tier can return the right
    ids with drifted distances, or vice versa)."""
    n = min(len(live_d), len(host_d))
    if n == 0:
        return 0.0
    lv = np.asarray(live_d[:n], dtype=np.float64)
    hv = np.asarray(host_d[:n], dtype=np.float64)
    ok = np.isfinite(lv) & np.isfinite(hv)
    if not ok.any():
        return 0.0
    denom = np.maximum(np.abs(hv[ok]), 1e-9)
    return float(np.mean(np.abs(lv[ok] - hv[ok]) / denom))


def score_batch(live_ids, live_dists, host_ids, host_dists, k: int):
    """Score one audited batch row-by-row -> (recall, rbo, relerr) means.
    Rows are trimmed to their valid (non-inf-distance) prefixes on both
    sides before scoring."""
    recalls, rbos, relerrs = [], [], []
    b = len(live_ids)
    for i in range(b):
        lv = np.asarray(live_dists[i])
        hv = np.asarray(host_dists[i])
        lids = np.asarray(live_ids[i])[~np.isinf(lv)]
        hids = np.asarray(host_ids[i])[~np.isinf(hv)]
        recalls.append(recall_at_k(lids, hids, k))
        rbos.append(rank_biased_overlap(lids, hids, k))
        relerrs.append(relative_distance_error(
            lv[~np.isinf(lv)], hv[~np.isinf(hv)]))
    n = max(len(recalls), 1)
    return (sum(recalls) / n, sum(rbos) / n, sum(relerrs) / n)


# -- the rolling window -------------------------------------------------------


class QualityWindow:
    """Rolling-window aggregate of audit scores (the PerfWindow idiom):
    per-tier sample deques evicted by time horizon, lifetime outcome
    counters, and per-tier EWMA recall for degradation detection.
    ``record``/``count`` are the worker-side entries (one small lock);
    ``summary()`` is the on-demand /debug/quality body."""

    def __init__(self, window_s: float = 300.0):
        self.window_s = max(float(window_s), 1e-3)
        self._lock = threading.Lock()
        # tier -> deque[(t_mono, recall, rbo, relerr, rows)]
        self._samples: dict[str, deque] = {}
        # tier -> EWMA recall (None until the first audit of that tier)
        self._ewma: dict[str, float] = {}
        self._ewma_n: dict[str, int] = {}
        self._degraded: dict[str, bool] = {}
        self._lag: deque = deque(maxlen=4096)  # (t_mono, lag_ms)
        # lifetime outcome counters (never evicted)
        self._counts = {"ok": 0, "shed": 0, "error": 0, "deadline": 0}
        self._captured = 0  # dispatches offered to the sampler
        self._sampled = 0   # dispatches the sampler picked

    # -- worker-side entries -------------------------------------------------

    def note_offered(self, sampled: bool) -> None:
        with self._lock:
            self._captured += 1
            if sampled:
                self._sampled += 1

    def count(self, outcome: str) -> None:
        with self._lock:
            self._counts[outcome] = self._counts.get(outcome, 0) + 1

    def record(self, tier: str, recall: float, rbo: float, relerr: float,
               rows: int, lag_ms: float,
               ewma_alpha: float = 0.2) -> tuple[float, int]:
        """Fold one completed audit in -> (tier EWMA recall, tier EWMA
        sample count) for the caller's degradation check."""
        now = time.monotonic()
        with self._lock:
            self._counts["ok"] += 1
            d = self._samples.get(tier)
            if d is None:
                d = self._samples[tier] = deque()
            d.append((now, recall, rbo, relerr, rows))
            self._lag.append((now, lag_ms))
            self._evict(now)
            prev = self._ewma.get(tier)
            ew = recall if prev is None else (
                ewma_alpha * recall + (1.0 - ewma_alpha) * prev)
            self._ewma[tier] = ew
            n = self._ewma_n.get(tier, 0) + 1
            self._ewma_n[tier] = n
            return ew, n

    def tier_ewmas(self) -> dict:
        """{tier: (recall EWMA, samples folded)} — the control plane's
        recall sensor (serving/controller.py steers the PQ candidate
        budget against it)."""
        with self._lock:
            return {t: (ew, self._ewma_n.get(t, 0))
                    for t, ew in self._ewma.items()}

    def set_degraded(self, tier: str, degraded: bool) -> bool:
        """-> True when this call TRANSITIONED the tier's state."""
        with self._lock:
            was = self._degraded.get(tier, False)
            self._degraded[tier] = degraded
            return was != degraded

    def _evict(self, now: float) -> None:
        horizon = now - self.window_s
        for d in self._samples.values():
            while d and d[0][0] < horizon:
                d.popleft()
        while self._lag and self._lag[0][0] < horizon:
            self._lag.popleft()

    # -- introspection -------------------------------------------------------

    def clear(self) -> None:
        """Reset the window and the EWMA state (bench measurement slices);
        lifetime counters survive, like PerfWindow's dispatch counter."""
        with self._lock:
            self._samples.clear()
            self._lag.clear()
            self._ewma.clear()
            self._ewma_n.clear()
            self._degraded.clear()

    def overall_recall(self) -> Optional[float]:
        """Row-weighted mean recall across every tier in the window (the
        bench row's ``online_recall`` field)."""
        now = time.monotonic()
        with self._lock:
            self._evict(now)
            num = den = 0.0
            for d in self._samples.values():
                for _, rec, _, _, rows in d:
                    num += rec * rows
                    den += rows
            return round(num / den, 4) if den > 0.0 else None

    def summary(self) -> dict:
        now = time.monotonic()
        with self._lock:
            self._evict(now)
            tiers: dict[str, dict] = {}
            for tier, d in self._samples.items():
                if not d:
                    continue
                recs = [r for _, r, _, _, _ in d]
                rbos = [r for _, _, r, _, _ in d]
                errs = [r for _, _, _, r, _ in d]
                tiers[tier] = {
                    "audits": len(d),
                    "rows": sum(r for _, _, _, _, r in d),
                    "recall_mean": round(sum(recs) / len(recs), 4),
                    "recall_min": round(min(recs), 4),
                    "rbo_mean": round(sum(rbos) / len(rbos), 4),
                    "distance_relerr_mean": round(
                        sum(errs) / len(errs), 6),
                    "recall_ewma": round(self._ewma[tier], 4)
                    if tier in self._ewma else None,
                    "degraded": self._degraded.get(tier, False),
                }
            lags = sorted(ms for _, ms in self._lag)
            counts = dict(self._counts)
            captured, sampled = self._captured, self._sampled
        out = {
            "window_s": self.window_s,
            "captured_dispatches": captured,
            "sampled_dispatches": sampled,
            "audits": counts,
            "tiers": tiers,
        }
        overall = self.overall_recall()
        if overall is not None:
            out["online_recall"] = overall
        if lags:
            out["audit_lag_ms"] = {
                "p50": round(_pct(lags, 50.0), 2),
                "p99": round(_pct(lags, 99.0), 2),
            }
        return out


def _pct(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(int(len(sorted_vals) * q / 100.0), len(sorted_vals) - 1)
    return float(sorted_vals[i])


# -- the auditor --------------------------------------------------------------


class _AuditTask:
    """One captured sample: everything the worker needs, copied or pinned
    at capture time so later index mutation cannot tear it. Constructed
    ONLY for sampled dispatches (the zero-cost contract's second half —
    tests spy-pin that the disabled path constructs none)."""

    __slots__ = ("vidx", "snap", "q", "k", "allow", "live_ids", "live_dists",
                 "t_captured", "class_name", "shard")

    def __init__(self, vidx, snap, q, k, allow, live_ids, live_dists,
                 class_name: str = "", shard: str = ""):
        self.vidx = vidx
        self.snap = snap  # the pinned IndexSnapshot the dispatch read
        self.q = q
        self.k = int(k)
        self.allow = allow
        self.live_ids = live_ids
        self.live_dists = live_dists
        self.t_captured = time.monotonic()
        self.class_name = class_name
        self.shard = shard


class QualityAuditor:
    """The process-wide shadow recall auditor. ``maybe_capture`` is the
    serving-path entry (sampling + drop-not-queue admission, a few array
    slices when sampled); audits execute on a tiny dedicated worker pool,
    strictly subordinate to live traffic."""

    def __init__(self, sample_rate: float, concurrency: int = 1,
                 max_rows: int = 64, deadline_ms: float = 1000.0,
                 window_s: float = 300.0, alert_threshold: float = 0.95,
                 alert_min_samples: int = 20, metrics=None,
                 start_workers: bool = True):
        self.sample_rate = min(max(float(sample_rate), 0.0), 1.0)
        self.concurrency = max(int(concurrency), 1)
        self.max_rows = max(int(max_rows), 1)
        self.deadline_ms = float(deadline_ms)
        self.alert_threshold = float(alert_threshold)
        self.alert_min_samples = max(int(alert_min_samples), 1)
        self.metrics = metrics
        self.window = QualityWindow(window_s)
        # drop-not-queue: a backlog of at most one pending task per worker
        # beyond the ones in flight; put_nowait on a full queue SHEDS the
        # sample (counted) instead of building a backlog behind live load
        self._queue: queue.Queue = queue.Queue(maxsize=self.concurrency)
        self._stop = threading.Event()
        # audits admitted (submit) but not yet scored — counted at
        # ADMISSION, not at worker pickup, so drain() can never report
        # idle while a popped-but-unscored task is still running
        self._inflight = 0
        self._lock = sanitizers.register_lock(
            threading.Lock(), "monitoring.quality")
        # id(index) -> (pinned snapshot, rows, sq_norms): consecutive
        # audits of one generation share the host materialization. ONE
        # entry per index — a new generation REPLACES the old, so the
        # cache can never pin several full-precision store copies of dead
        # generations — bounded to a few indexes, and auditor-owned so
        # audits never touch the breaker's fallback cache
        self._rows_cache: dict = {}
        self._degraded_last_log: dict[str, float] = {}
        # host-memory provider (monitoring/memory.py): the audit rows
        # cache — full-precision store copies — becomes a /debug/memory
        # host component, sized by the same helper /debug/index uses
        from weaviate_tpu.monitoring import memory

        memory.register_host_provider(self, memory.auditor_host_components)
        self._threads: list[threading.Thread] = []
        if start_workers:
            for i in range(self.concurrency):
                t = threading.Thread(target=self._worker, daemon=True,
                                     name=f"quality-audit-{i}")
                t.start()
                self._threads.append(t)

    # -- serving-path capture ------------------------------------------------

    def maybe_capture(self, vidx, snap, q, k: int, allow, live_ids,
                      live_dists, class_name: str = "",
                      shard: str = "") -> bool:
        """Sample one completed live search. Called by db/shard.py at
        finalize with the snapshot the dispatch read (already popped from
        the index TLS pin). -> True when a task was admitted."""
        sampled = random.random() < self.sample_rate
        self.window.note_offered(sampled)
        if not sampled:
            return False
        q = np.asarray(q)
        live_ids = np.asarray(live_ids)
        live_dists = np.asarray(live_dists)
        if q.ndim == 1:
            q = q[None, :]
        b = q.shape[0]
        if live_ids.ndim != 2 or live_ids.shape[0] != b or b == 0:
            # foreign result shape: nothing to score — counted, so
            # sampled_dispatches can never silently outrun the outcome
            # counters (the "auditor not auditing" state must be visible)
            self.window.count("skipped")
            self._count_metric("skipped")
            return False
        if b > self.max_rows:
            # row budget: audit a uniform subset of the batch's rows
            sel = np.sort(np.random.default_rng().choice(
                b, self.max_rows, replace=False))
            q, live_ids, live_dists = q[sel], live_ids[sel], live_dists[sel]
        task = _AuditTask(vidx, snap, np.array(q, copy=True), k, allow,
                          np.array(live_ids, copy=True),
                          np.array(live_dists, copy=True),
                          class_name=class_name, shard=shard)
        return self.submit(task)

    def submit(self, task: _AuditTask) -> bool:
        """Admit a task under the drop-not-queue bound; -> False = shed.
        The inflight count moves BEFORE the enqueue (rolled back on a
        full queue) so it can never under-report a task a worker already
        popped but has not finished scoring."""
        with self._lock:
            self._inflight += 1
        try:
            self._queue.put_nowait(task)
            return True
        except queue.Full:
            with self._lock:
                self._inflight -= 1
            self.window.count("shed")
            self._count_metric("shed")
            return False

    # -- the background worker (exception-guarded run loop: a silently
    # dead audit thread would read as recall=perfect — graftlint JGL011) --

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                task = self._queue.get(timeout=0.25)
            except queue.Empty:
                continue
            if task is None:
                continue  # shutdown wake-up sentinel (never counted)
            try:
                self._run_audit(task)
            except AuditDeadlineExceeded:
                self.window.count("deadline")
                self._count_metric("deadline")
            except Exception:  # noqa: BLE001 — the audit loop must survive
                self.window.count("error")
                self._count_metric("error")
                _LOG.warning("quality audit failed", exc_info=True)
            finally:
                with self._lock:
                    self._inflight -= 1

    def _host_rows(self, vidx, snap):
        """Per-index cached host materialization: one (snapshot, rows,
        norms) entry per index, replaced whenever an audit pins a newer
        snapshot — so the cache never accumulates full-precision store
        copies of dead generations. Snapshot IDENTITY (not gen) keys the
        hit, so a recycled id(vidx) after GC can never serve another
        index's rows. Auditor-owned: the breaker's fallback cache
        (released on recovery) is never touched."""
        key = id(vidx)
        with self._lock:
            hit = self._rows_cache.get(key)
            if hit is not None and hit[0] is snap:
                # LRU move-to-end on hit: a plain re-assign keeps the
                # dict position, and FIFO would evict the HOTTEST index
                self._rows_cache.pop(key)
                self._rows_cache[key] = hit
                return hit[1], hit[2]
        rows, sq = vidx.host_rows(snap)
        with self._lock:
            self._rows_cache.pop(key, None)  # move-to-end on update too
            self._rows_cache[key] = (snap, rows, sq)
            while len(self._rows_cache) > 4:  # a few indexes at most
                self._rows_cache.pop(next(iter(self._rows_cache)))
        return rows, sq

    def _run_audit(self, task: _AuditTask) -> None:
        lag_ms = (time.monotonic() - task.t_captured) * 1000.0
        deadline = (time.monotonic() + self.deadline_ms / 1000.0
                    if self.deadline_ms > 0 else None)
        vidx, snap = task.vidx, task.snap
        tier = vidx.dispatch_tier(snap, task.allow)
        rows, sq = self._host_rows(vidx, snap)
        host_ids, host_d = vidx.search_by_vectors_host_pinned(
            snap, task.q, task.k, task.allow, rows=rows, sq_norms=sq,
            deadline=deadline)
        recall, rbo, relerr = score_batch(
            task.live_ids, task.live_dists, host_ids, host_d, task.k)
        self._observe(tier, recall, rbo, relerr, task.q.shape[0], lag_ms)

    def _observe(self, tier: str, recall: float, rbo: float, relerr: float,
                 rows: int, lag_ms: float) -> None:
        """Fold one audit's scores in: window, gauges, degradation check.
        Split out so tests can drive the detector deterministically."""
        ewma, n = self.window.record(tier, recall, rbo, relerr, rows, lag_ms)
        m = self.metrics
        if m is not None:
            try:
                m.recall_at_k.labels(tier).set(round(ewma, 4))
                m.distance_relerr.labels(tier).set(round(relerr, 6))
                m.quality_audits.labels("ok").inc()
                m.quality_audit_lag.observe(lag_ms)
            except Exception:  # noqa: BLE001 — metrics must not kill audits
                pass
        if n < self.alert_min_samples:
            return
        degraded = ewma < self.alert_threshold
        transitioned = self.window.set_degraded(tier, degraded)
        if degraded:
            if transitioned and m is not None:
                try:
                    m.quality_degraded.labels(tier).inc()
                except Exception:  # noqa: BLE001
                    pass
            if transitioned:
                # the degradation transition is an ops-journal event AND an
                # incident trigger (monitoring/incidents.py): the bundle
                # preserves the quality window + journal around the drop.
                # One-comparison no-ops when the plane is off; lazy import
                # (incidents is deliberately off this module's import path).
                try:
                    from weaviate_tpu.monitoring import incidents

                    incidents.emit("quality_degraded", scope=tier,
                                   ewma_recall=round(ewma, 4),
                                   threshold=self.alert_threshold)
                    incidents.trigger(
                        "quality_degraded",
                        reason=f"online recall degraded: tier={tier} "
                               f"ewma={ewma:.4f} < {self.alert_threshold}",
                        detail={"tier": tier, "ewma_recall": ewma})
                except Exception:  # noqa: BLE001 — must not break the audit loop
                    pass
            now = time.monotonic()
            last = self._degraded_last_log.get(tier)
            if last is None or now - last >= DEGRADED_LOG_INTERVAL_S:
                self._degraded_last_log[tier] = now
                _LOG.warning(
                    "online recall degraded: tier=%s ewma_recall=%.4f "
                    "threshold=%.4f (over >= %d audited dispatches) — "
                    "counted in weaviate_quality_degraded_total; further "
                    "lines rate-limited to one per %.0fs",
                    tier, ewma, self.alert_threshold,
                    self.alert_min_samples, DEGRADED_LOG_INTERVAL_S)
        elif transitioned:
            _LOG.info("online recall recovered: tier=%s ewma_recall=%.4f",
                      tier, ewma)
            try:
                from weaviate_tpu.monitoring import incidents

                incidents.emit("quality_recovered", scope=tier,
                               ewma_recall=round(ewma, 4))
            except Exception:  # noqa: BLE001 — must not break the audit loop
                pass

    def _count_metric(self, outcome: str) -> None:
        m = self.metrics
        if m is not None:
            try:
                m.quality_audits.labels(outcome).inc()
            except Exception:  # noqa: BLE001
                pass

    # -- introspection / lifecycle -------------------------------------------

    def set_sample_rate(self, rate: float) -> None:
        """Adjust the capture sampling gate (clamped to [0, 1]). The
        control plane's brownout stage 3 pauses auditing with 0 and
        restores the configured rate on recovery/revert — workers stay
        up, the gate is what moves (serving/controller.py is the ONLY
        caller outside tests; graftlint JGL014 pins that)."""
        self.sample_rate = min(max(float(rate), 0.0), 1.0)

    def tier_ewmas(self) -> dict:
        """{tier: (recall EWMA, samples)} — see QualityWindow.tier_ewmas."""
        return self.window.tier_ewmas()

    def summary(self) -> dict:
        out = self.window.summary()
        out["sample_rate"] = self.sample_rate
        out["concurrency"] = self.concurrency
        out["max_rows"] = self.max_rows
        out["deadline_ms"] = self.deadline_ms
        out["alert_threshold"] = self.alert_threshold
        return out

    def clear(self) -> None:
        self.window.clear()

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Block until every admitted audit completed (bench/test sync
        point; never used on the serving path). Inflight counts from
        ADMISSION to scored, so a task a worker has popped but not
        finished still holds the count. -> False on timeout."""
        end = time.monotonic() + timeout_s
        while time.monotonic() < end:
            with self._lock:
                idle = self._inflight == 0
            if idle:
                return True
            time.sleep(0.01)
        return False

    def shutdown(self) -> None:
        self._stop.set()
        for _ in self._threads:
            try:
                self._queue.put_nowait(None)  # wake blocked workers
            except queue.Full:
                break
        for t in self._threads:
            t.join(timeout=2)


# -- module state + zero-hop accessors ----------------------------------------

_auditor: Optional[QualityAuditor] = None

# final summaries of recently-unconfigured auditors (CI failure artifact:
# tests/conftest.py dumps these alongside the perf summaries). Guarded by
# its own lock — concurrent App teardowns share it (the perf.py pattern).
_final_summaries: deque = deque(maxlen=8)
_summaries_lock = threading.Lock()


def configure(auditor: Optional[QualityAuditor]) -> Optional[QualityAuditor]:
    """Install (or clear, with None) the process-wide auditor."""
    global _auditor
    _auditor = auditor
    return auditor


def unconfigure(auditor: QualityAuditor) -> None:
    """Clear the global only if it is still `auditor` (App shutdown must
    not tear down a newer App's auditor); stash its final summary for the
    CI artifact dump when it scored anything; stop its workers."""
    global _auditor
    try:
        doc = auditor.summary()
        if doc.get("audits", {}).get("ok") or doc.get("sampled_dispatches"):
            with _summaries_lock:
                _final_summaries.append(doc)
    except Exception:  # noqa: BLE001 — teardown must never fail shutdown
        pass
    if _auditor is auditor:
        _auditor = None
    auditor.shutdown()


def get_auditor() -> Optional[QualityAuditor]:
    return _auditor


def recent_summaries() -> list:
    """Final summaries of auditors torn down this process (newest last),
    plus the live auditor's current summary when one is installed."""
    with _summaries_lock:
        out = list(_final_summaries)
    a = _auditor
    if a is not None:
        try:
            out.append(a.summary())
        except Exception:  # noqa: BLE001
            pass
    return out
