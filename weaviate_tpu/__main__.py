"""Process entry point: `python -m weaviate_tpu`.

Reference: cmd/weaviate-server/main.go:30 — load config from the
environment, assemble the whole object graph, serve REST (+ metrics when
enabled) and gRPC until SIGTERM/SIGINT, then shut down cleanly.

Flags mirror the reference's swagger flags where they matter:
    --host (default 0.0.0.0), --port (default 8080; PORT env also honored),
    --grpc-port (default GRPC_PORT env / 50051), --data-path (overrides
    PERSISTENCE_DATA_PATH). Everything else comes from the env-var surface
    (usecases/config/environment.go twin in weaviate_tpu/config).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="weaviate-tpu", description=__doc__)
    ap.add_argument("--host", default=os.environ.get("HOST", "0.0.0.0"))
    ap.add_argument("--port", type=int, default=int(os.environ.get("PORT", "8080")))
    ap.add_argument("--grpc-port", type=int, default=None)
    ap.add_argument("--data-path", default=None)
    args = ap.parse_args(argv)

    # honor JAX_PLATFORMS even when a site hook (sitecustomize) imported
    # jax before this process's env was consulted — the 12-factor contract
    # is that the container env picks the backend, and without this a host
    # that pins a device backend silently overrides `JAX_PLATFORMS=cpu`
    # (first insert then blocks on an unreachable accelerator)
    if os.environ.get("JAX_PLATFORMS"):
        try:
            import jax

            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except Exception as e:  # noqa: BLE001 — serving beats backend pinning
            print(f"warning: could not apply JAX_PLATFORMS: {e}", flush=True)

    from weaviate_tpu.config import load_config
    from weaviate_tpu.server import App, RestServer
    from weaviate_tpu.server.grpc_server import GrpcServer
    from weaviate_tpu.version import __version__

    config = load_config()
    app = App(config=config, data_path=args.data_path)
    rest = RestServer(app, host=args.host, port=args.port)
    grpc_port = args.grpc_port if args.grpc_port is not None else config.grpc_port
    grpc_srv = GrpcServer(app, host=args.host, port=grpc_port)

    stop = threading.Event()

    def handle(signum, frame):
        print(f"received signal {signum}, shutting down", flush=True)
        stop.set()

    # handlers BEFORE the listeners come up: a supervisor that signals the
    # moment readiness flips must hit the graceful path, not the default
    # action
    signal.signal(signal.SIGTERM, handle)
    signal.signal(signal.SIGINT, handle)

    rest.start()
    grpc_srv.start()
    parts = [f"REST http://{args.host}:{rest.port}", f"gRPC {args.host}:{grpc_srv.port}"]
    if getattr(rest, "_metrics_httpd", None) is not None:
        parts.append(f"metrics :{rest.metrics_port}")
    if app.cluster_node is not None:
        parts.append(f"clusterapi {app.cluster_node.address}")
    print(f"weaviate-tpu {__version__} serving " + ", ".join(parts), flush=True)
    stop.wait()

    grpc_srv.stop()
    rest.stop()
    app.shutdown()
    print("shutdown complete", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
