"""Fault-injection harness for the TPU serving path.

Robustness claims ("the breaker trips and host fallback serves", "a dead
flush thread can't hang a client") are only real if the failures they
defend against are REPRODUCIBLE. Real device faults — an allocator OOM
mid-dispatch, an XlaRuntimeError at fetch, a wedged kernel — can't be
summoned in CI, so the serving path carries NAMED INJECTION POINTS and
this module decides, deterministically, what happens at each one.

Injection points (the fault matrix; see docs/robustness.md):

  index.tpu.dispatch       device work enqueue (index/tpu.py
                           _dispatch_search) — device-error-on-dispatch
  index.tpu.finalize       the blocking device->host fetch — slow-kernel
                           stall, device-error-at-fetch
  index.tpu.alloc          store growth (index/tpu.py _ensure_capacity) —
                           allocator OOM on the write path
  db.shard.search          shard read entry (db/shard.py) — pre-dispatch
                           failure
  serving.coalescer.flush  the flush loop (serving/coalescer.py _run) —
                           flush-thread death (a BaseException that
                           escapes the loop's `except Exception` defense)
  serving.coalescer.dispatch  per-lane flush — lane dispatch failure
  serving.coalescer.admit  admission (serving/coalescer.py submit, before
                           any queue state is touched) — the
                           abusive-tenant storm journeys stall/fail
                           requests AT admission to stress the
                           weighted-fair queue under chaos
  serving.controller.tick  the control plane's tick loop (serving/
                           controller.py _run) — `die` kills the
                           controller thread (its finally must revert
                           every actuated knob to its configured
                           default: fail-static), `stall` freezes it
                           (module-read knob leases must lapse to
                           defaults); either way serving never degrades

Actions: ``device_error`` / ``oom`` raise errors that
``robustness.is_device_error`` recognizes (they carry ``device_error =
True``), ``stall`` sleeps, ``die`` raises ``InjectedThreadDeath``
(BaseException — deliberately uncatchable by `except Exception` so it
kills the hosting thread the way a real thread death would), and tests
may pass a callable.

Determinism: a plan fires on an exact firing-count window (``after`` /
``times``), or Bernoulli with probability ``p`` drawn from a
``random.Random(seed)`` owned by the injector — the same seed replays the
same failure schedule, so failure journeys are reproducible in CI.

Zero-cost when disabled (the tracing.py pattern): the module global is
None and ``fire()`` returns after one comparison — no locks, no dict
lookups, nothing allocated on the serving hot path.

Gating: tests call ``configure()`` directly; a running server enables it
via ``FAULT_INJECTION`` (spec string, parsed by ``from_spec``) +
``FAULT_INJECTION_SEED`` — config/config.py, wired in server/app.py.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional, Union


class FaultError(RuntimeError):
    """Base class for injected failures."""


class InjectedDeviceError(FaultError):
    """Stands in for jaxlib's XlaRuntimeError at a dispatch boundary.
    ``device_error`` is the attribute contract robustness.is_device_error
    keys on (the real class is recognized by name/module)."""

    device_error = True


class InjectedOOMError(InjectedDeviceError):
    """RESOURCE_EXHAUSTED / allocator-OOM analog."""


class InjectedThreadDeath(BaseException):
    """Deliberately a BaseException: escapes `except Exception` defenses,
    killing the hosting thread — the shape of a real thread death (C
    extension abort, MemoryError mid-handler) that liveness code must
    survive."""


_ACTIONS = ("device_error", "oom", "stall", "die")

Action = Union[str, Callable[[str], None]]


class _Plan:
    __slots__ = ("point", "action", "after", "times", "p", "stall_s", "hits")

    def __init__(self, point: str, action: Action, after: int, times:
                 Optional[int], p: float, stall_s: float):
        self.point = point
        self.action = action
        self.after = max(int(after), 0)
        self.times = times  # None = forever
        self.p = float(p)
        self.stall_s = float(stall_s)
        self.hits = 0  # times this plan actually fired


class FaultInjector:
    """Holds the failure schedule; thread-safe; deterministic per seed."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self._plans: list[_Plan] = []
        self._fired: dict[str, int] = {}   # point -> firings observed
        self._injected: dict[str, int] = {}  # point -> faults injected

    def plan(self, point: str, action: Action = "device_error", *,
             times: Optional[int] = 1, after: int = 0, p: float = 1.0,
             stall_s: float = 0.05) -> "FaultInjector":
        """Inject `action` at `point`: skip the first `after` eligible
        firings, then inject on up to `times` of the following ones (None =
        every one), each gated by Bernoulli(p) on the injector's seeded
        rng. Returns self for chaining."""
        if isinstance(action, str) and action not in _ACTIONS:
            raise ValueError(f"unknown fault action {action!r} "
                             f"(want one of {_ACTIONS} or a callable)")
        with self._lock:
            self._plans.append(_Plan(point, action, after, times, p, stall_s))
        return self

    def clear(self, point: Optional[str] = None) -> None:
        """Drop plans (all, or one point's) — 'the fault stops happening'."""
        with self._lock:
            self._plans = [pl for pl in self._plans
                           if point is not None and pl.point != point]

    def fired(self, point: str) -> int:
        """Times `point` was reached (injected or not)."""
        with self._lock:
            return self._fired.get(point, 0)

    def injected(self, point: Optional[str] = None) -> int:
        """Faults actually injected (at one point, or in total)."""
        with self._lock:
            if point is not None:
                return self._injected.get(point, 0)
            return sum(self._injected.values())

    def fire(self, point: str) -> None:
        """Decide-and-act for one arrival at `point`. The decision happens
        under the lock (counts + seeded rng draws stay deterministic under
        threads only when the arrival ORDER is deterministic — exact-count
        windows, the CI-friendly mode, are order-independent); the action
        runs outside it (a stall must not serialize unrelated points)."""
        act: Optional[tuple[Action, float]] = None
        with self._lock:
            self._fired[point] = self._fired.get(point, 0) + 1
            for pl in self._plans:
                if pl.point != point:
                    continue
                if pl.after > 0:
                    pl.after -= 1
                    continue
                if pl.times is not None and pl.hits >= pl.times:
                    continue
                if pl.p < 1.0 and self._rng.random() >= pl.p:
                    continue
                pl.hits += 1
                self._injected[point] = self._injected.get(point, 0) + 1
                act = (pl.action, pl.stall_s)
                break
        if act is None:
            return
        action, stall_s = act
        # journal the injection (monitoring/incidents.py): a seeded storm's
        # firings then appear in the incident bundle's journal tail next to
        # the breaker/shed events they caused — the fault matrix becomes
        # legible post-mortem. Burst-coalesced per point; one-comparison
        # no-op when the plane is off; lazy import keeps this module's
        # zero-dependency import contract.
        try:
            from weaviate_tpu.monitoring import incidents

            incidents.emit("fault_injected", scope=point,
                           action=action if isinstance(action, str)
                           else "callable")
        except Exception:  # noqa: BLE001 — injection bookkeeping must not mask the fault
            pass
        if callable(action):
            action(point)
        elif action == "stall":
            time.sleep(stall_s)
        elif action == "oom":
            raise InjectedOOMError(
                f"injected RESOURCE_EXHAUSTED: allocator OOM at {point}")
        elif action == "die":
            raise InjectedThreadDeath(f"injected thread death at {point}")
        else:
            raise InjectedDeviceError(
                f"injected device failure at {point} "
                "(XlaRuntimeError analog)")


def from_spec(spec: str, seed: int = 0) -> FaultInjector:
    """Parse the ``FAULT_INJECTION`` config string into an injector.

    Spec: semicolon-separated plans, each
    ``point:action[:key=value...]`` with keys ``times`` (int or ``inf``),
    ``after`` (int), ``p`` (float), ``stall_ms`` (float). Example::

        index.tpu.dispatch:device_error:times=inf:p=0.3;\
        serving.coalescer.flush:die:after=10
    """
    inj = FaultInjector(seed=seed)
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise ValueError(
                f"invalid FAULT_INJECTION plan {part!r} (want point:action)")
        point, action = fields[0].strip(), fields[1].strip()
        kw: dict = {}
        for f in fields[2:]:
            if "=" not in f:
                raise ValueError(f"invalid FAULT_INJECTION option {f!r}")
            k, v = f.split("=", 1)
            k = k.strip()
            if k == "times":
                kw["times"] = None if v.strip() == "inf" else int(v)
            elif k == "after":
                kw["after"] = int(v)
            elif k == "p":
                kw["p"] = float(v)
            elif k == "stall_ms":
                kw["stall_s"] = float(v) / 1000.0
            else:
                raise ValueError(f"unknown FAULT_INJECTION option {k!r}")
        inj.plan(point, action, **kw)
    return inj


# -- module state + the zero-hop entry point ----------------------------------

_injector: Optional[FaultInjector] = None


def configure(injector: Optional[FaultInjector]) -> Optional[FaultInjector]:
    """Install (or clear, with None) the process-wide injector."""
    global _injector
    _injector = injector
    return injector


def unconfigure(injector: FaultInjector) -> None:
    """Clear only if still `injector` (App shutdown must not tear down a
    newer App's harness)."""
    global _injector
    if _injector is injector:
        _injector = None


def get_injector() -> Optional[FaultInjector]:
    return _injector


def fire(point: str) -> None:
    """The per-injection-point hook on the serving path. Disabled => one
    comparison, nothing else."""
    inj = _injector
    if inj is None:
        return
    inj.fire(point)
