"""graftsan: runtime concurrency sanitizers for the serving plane.

docs/concurrency.md documents a lock hierarchy; graftlint's JGL005/JGL008/
JGL009 check it *lexically*, per file. Neither can see a sync hidden one
call deep at runtime, a lock-order inversion spanning two modules, or a
tick/audit thread that outlives its App. Before the dispatch-engine
refactor (ROADMAP items 2/5) rearranges ~10 concurrent module-global
threads against that hierarchy, this module makes the documented
discipline *witnessed*: a ThreadSanitizer-style runtime checker that
tier-1 runs under in CI (``GRAFTSAN=1``; tests/conftest.py).

Three sanitizers (enable subsets via ``GRAFTSAN=lock,sync,threads``):

  lock-order   Locks the serving modules construct are wrapped by
               ``register_lock(lock, name)`` in an order-witnessing proxy.
               Each blocking acquire records (held -> acquiring) edges into
               a global acquisition-order graph with both stacks; a cycle
               (the AB/BA shape) is a potential-deadlock violation even if
               the schedule never actually deadlocks, and an acquisition
               that *descends* the machine-readable hierarchy table
               (tools/graftsan/lock_hierarchy.json, the runtime twin of
               the docs/concurrency.md table) is a hierarchy violation.
  device-sync  The runtime twin of JGL001/JGL008: the repo's device->host
               fetch points (``np.asarray`` on a jax array,
               ``jax.block_until_ready``, index/tpu.py ``_fetch_packed``)
               are patched to assert no registered index/shard lock
               (``no_fetch_under`` in the hierarchy table) is held at
               fetch time — catching what lexical analysis misses when
               the sync hides behind a helper function.
  thread-leak  Per-test thread snapshot diffing (tests/conftest.py): a
               test that leaks a non-daemon thread, or a daemon thread of
               a module-global serving plane (coalescer flusher,
               controller tick, audit workers, incident recorder) past
               its App shutdown / unconfigure, fails that test instead of
               surfacing later as a flaky cross-test timeout.

Zero-cost when disabled (the tracing/perf/faults lifecycle idiom): the
module global is ``None``, ``register_lock`` returns its argument after
one comparison (the serving path keeps its raw ``threading`` locks — no
proxy is ever constructed), and no fetch point is patched. Pinned by a
spy test through a real served search (tests/test_sanitizers.py).

Violations are deduplicated by key and checked against the shrink-only
runtime baseline (tools/graftsan/baseline.json): a justified pre-existing
hit (e.g. the mesh index's stop-the-world ``compact`` fetching under its
coarse lock) is recorded, counted, and waived; anything else fails the
test that triggered it and lands in the ``GRAFTSAN_REPORT_FILE`` JSON
report (``python -m tools.graftsan --report`` renders one).

Gating: tests/conftest.py configures from the ``GRAFTSAN`` env var
(parsed by ``parse_graftsan``); ci_check.sh exports ``GRAFTSAN=1`` for
the tier-1 stage. The module imports stdlib only — jax/numpy load
lazily at configure time, so importing the registry costs nothing.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Optional

# the three sanitizer planes GRAFTSAN can enable
LOCK_ORDER = "lock"
DEVICE_SYNC = "sync"
THREAD_LEAK = "threads"
ALL_SANITIZERS = frozenset({LOCK_ORDER, DEVICE_SYNC, THREAD_LEAK})

_FALSY = frozenset({"", "0", "false", "no", "off"})
_TRUTHY = frozenset({"1", "true", "yes", "on", "all"})

# module-global thread-name prefixes the leak detector watches even though
# they are daemon threads: each belongs to a plane whose App shutdown /
# unconfigure MUST stop it — one leaking past teardown today survives
# silently until an unrelated test flakes on its background work
WATCHED_THREAD_PREFIXES = (
    "query-coalescer",
    "coalescer-dispatch",
    "serving-controller",
    "quality-audit-",
    "incident-recorder",
)

# tools/graftsan/lock_hierarchy.json + baseline.json, anchored at the repo
# root the way graftlint anchors its baseline (never the cwd)
_REPO_ROOT = os.path.realpath(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
DEFAULT_HIERARCHY_PATH = os.path.join(
    _REPO_ROOT, "tools", "graftsan", "lock_hierarchy.json")
DEFAULT_BASELINE_PATH = os.path.join(
    _REPO_ROOT, "tools", "graftsan", "baseline.json")


def parse_graftsan(value: Optional[str]) -> frozenset:
    """``GRAFTSAN`` env value -> the set of enabled sanitizers.

    ``""``/``0``/``false`` -> none; ``1``/``true``/``all`` -> all three;
    a comma list (``lock,sync``) -> that subset. An unknown token raises
    ``ValueError`` — a typo'd sanitizer name must not silently run
    *nothing* and report green."""
    v = (value or "").strip().lower()
    if v in _FALSY:
        return frozenset()
    if v in _TRUTHY:
        return ALL_SANITIZERS
    out = set()
    for tok in v.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok not in ALL_SANITIZERS:
            raise ValueError(
                f"unknown GRAFTSAN sanitizer {tok!r} "
                f"(want 0/1 or a comma list of {sorted(ALL_SANITIZERS)})")
        out.add(tok)
    return frozenset(out)


def load_hierarchy(path: Optional[str] = None) -> dict:
    """lock_hierarchy.json -> {name: {level, no_fetch_under}}. Raises on a
    malformed table: a silently-empty hierarchy would witness nothing."""
    with open(path or DEFAULT_HIERARCHY_PATH, encoding="utf-8") as f:
        data = json.load(f)
    locks = data.get("locks")
    if not isinstance(locks, list) or not locks:
        raise ValueError("lock_hierarchy.json must hold a 'locks' list")
    out: dict[str, dict] = {}
    for e in locks:
        name = e.get("name")
        if not isinstance(name, str) or not name:
            raise ValueError(f"lock hierarchy entry without a name: {e!r}")
        if name in out:
            raise ValueError(f"duplicate lock hierarchy entry {name!r}")
        if not isinstance(e.get("level"), int):
            raise ValueError(f"lock {name!r}: 'level' must be an int")
        out[name] = {"level": int(e["level"]),
                     "no_fetch_under": bool(e.get("no_fetch_under", False))}
    return out


def _load_baseline(path: Optional[str]) -> list[dict]:
    p = path or DEFAULT_BASELINE_PATH
    if not os.path.exists(p):
        return []
    with open(p, encoding="utf-8") as f:
        data = json.load(f)
    entries = data.get("entries", [])
    if not isinstance(entries, list):
        raise ValueError(f"{p}: baseline must hold an 'entries' list")
    return entries


class Violation:
    """One deduplicated sanitizer finding. ``key`` identifies the finding
    class (repeat occurrences bump ``count``); ``stacks`` carries the
    acquisition/fetch stacks of the FIRST occurrence."""

    __slots__ = ("kind", "key", "message", "stacks", "count", "baselined",
                 "justification")

    def __init__(self, kind: str, key: tuple, message: str,
                 stacks: list[str]):
        self.kind = kind
        self.key = key
        self.message = message
        self.stacks = stacks
        self.count = 1
        self.baselined = False
        self.justification: Optional[str] = None

    def render(self) -> str:
        head = f"[{self.kind}] {self.message} (x{self.count})"
        if self.baselined:
            head += f"  [baselined: {self.justification}]"
        return "\n".join([head] + [s.rstrip() for s in self.stacks])

    def as_dict(self) -> dict:
        return {"kind": self.kind, "key": list(self.key),
                "message": self.message, "count": self.count,
                "baselined": self.baselined,
                "justification": self.justification,
                "stacks": self.stacks}


def _grab_stack():
    """The acquisition stack, captured CHEAPLY: frame triples only, no
    source-line lookup (``lookup_lines=False`` defers linecache to
    render time, which only a violation ever reaches). Skips the
    sanitizer's own two frames. Kept fast because EVERY registered-lock
    acquire pays this — the witness must not reorder the races it
    watches more than it has to."""
    f = sys._getframe(2)
    return traceback.StackSummary.extract(
        traceback.walk_stack(f), limit=14, lookup_lines=False)


def _fmt_stack(stack) -> str:
    # captured innermost-first by walk_stack; render outermost-first the
    # way tracebacks read
    return "".join(traceback.format_list(list(reversed(stack))))


class _Held:
    """One entry of a thread's held-lock stack. ``stack`` is an
    unformatted traceback.StackSummary (formatting costs ~100x more than
    extraction and is paid only when a violation reports it)."""

    __slots__ = ("lock", "count", "stack")

    def __init__(self, lock: "_SanLock", stack):
        self.lock = lock
        self.count = 1
        self.stack = stack


class _SanLock:
    """Order-witnessing proxy around a real Lock/RLock. The inner lock
    does the actual synchronization; the proxy only records held-lock
    stacks per thread and feeds the acquisition-order graph. Condition
    compatibility: threading.Condition binds ``acquire``/``release`` (and
    the ``_release_save`` family when present) off the object it is given
    — the proxy defines all of them so a Condition built over a
    registered lock keeps the bookkeeping exact across ``wait()``."""

    __slots__ = ("_inner", "name", "_san")

    def __init__(self, inner, name: str, san: "GraftSan"):
        self._inner = inner
        self.name = name
        self._san = san

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            # witness BEFORE blocking: the order fact exists whether or
            # not this schedule actually deadlocks
            self._san._note_acquiring(self)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._san._note_acquired(self)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._san._note_released(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        return self._inner.locked()

    # -- threading.Condition integration (wait() releases then reacquires) --

    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        # plain Lock: owned iff a non-blocking acquire fails (the stdlib
        # Condition fallback, done here so bookkeeping never sees it)
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _release_save(self):
        if hasattr(self._inner, "_release_save"):
            state = self._inner._release_save()
        else:
            self._inner.release()
            state = None
        self._san._note_release_all(self)
        return state

    def _acquire_restore(self, state) -> None:
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._san._note_acquiring(self)
        self._san._note_acquired(self)

    def __repr__(self) -> str:
        return f"<graftsan lock {self.name!r} over {self._inner!r}>"


class GraftSan:
    """The sanitizer registry + witness state. One instance is installed
    process-wide via ``configure``; tests may also construct private
    instances and drive them directly (tests/test_sanitizers.py)."""

    def __init__(self, enabled: frozenset = ALL_SANITIZERS,
                 hierarchy: Optional[dict] = None,
                 baseline: Optional[list] = None,
                 hierarchy_path: Optional[str] = None,
                 baseline_path: Optional[str] = None):
        self.enabled = frozenset(enabled)
        self.hierarchy = (hierarchy if hierarchy is not None
                          else load_hierarchy(hierarchy_path))
        self._baseline = (baseline if baseline is not None
                          else _load_baseline(baseline_path))
        self._tls = threading.local()          # .held: list[_Held]
        self._state_lock = threading.Lock()    # graph + violations (leaf:
        # nothing is acquired under it, so it can never join a cycle)
        # (from_name, to_name) -> {"stack_from", "stack_to", "thread"}
        self._edges: dict[tuple, dict] = {}
        self._violations: dict[tuple, Violation] = {}
        self._order: list[Violation] = []      # insertion order, for since()
        self.locks_registered: dict[str, int] = {}
        self.fetch_checks = 0                  # device-sync assertions run

    # -- registration ---------------------------------------------------------

    def wrap_lock(self, lock, name: str):
        # the device-sync sanitizer needs the held-lock bookkeeping the
        # proxy maintains — sync without lock must still proxy, or
        # check_fetch sees an empty held stack and silently reports green
        if not (self.enabled & {LOCK_ORDER, DEVICE_SYNC}):
            return lock
        with self._state_lock:
            self.locks_registered[name] = \
                self.locks_registered.get(name, 0) + 1
        return _SanLock(lock, name, self)

    # -- held-lock bookkeeping ------------------------------------------------

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def held_lock_names(self) -> list[str]:
        return [h.lock.name for h in self._held()]

    def _note_acquiring(self, lock: _SanLock) -> None:
        if LOCK_ORDER not in self.enabled:
            return  # proxied only for the sync sanitizer's held bookkeeping
        held = self._held()
        if not held:
            return  # first lock of this thread: no order fact to record
        if any(h.lock is lock for h in held):
            return  # re-entrant acquire of an RLock: not an ordering edge
        stack_to = _grab_stack()
        top = held[-1]
        self._record_edge(top, lock, stack_to)
        self._check_hierarchy(held, lock, stack_to)

    def _note_acquired(self, lock: _SanLock) -> None:
        held = self._held()
        for h in held:
            if h.lock is lock:
                h.count += 1
                return
        held.append(_Held(lock, _grab_stack()))

    def _note_released(self, lock: _SanLock) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is lock:
                held[i].count -= 1
                if held[i].count == 0:
                    del held[i]
                return

    def _note_release_all(self, lock: _SanLock) -> None:
        """Condition.wait released the lock wholesale (RLock recursion
        included) — drop the whole entry."""
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is lock:
                del held[i]
                return

    # -- the acquisition-order graph -----------------------------------------

    def _record_edge(self, frm: _Held, to: _SanLock, stack_to) -> None:
        """held(frm) -> acquiring(to). A new edge that closes a cycle in
        the graph is the AB/BA potential deadlock; report it with both
        acquisition stacks (this thread's, and the recorded stack of the
        reverse path's first edge)."""
        a, b = frm.lock.name, to.name
        if a == b:
            # two distinct same-name locks (two shards' "db.shard") held
            # together: legal nesting order is undefined but symmetric;
            # the hierarchy check stays silent and a self-edge would make
            # every pair a "cycle", so skip the graph too
            return
        with self._state_lock:
            is_new = (a, b) not in self._edges
            if is_new:
                self._edges[(a, b)] = {
                    "stack_from": frm.stack, "stack_to": stack_to,
                    "thread": threading.current_thread().name}
            if not is_new:
                return
            path = self._find_path(b, a)
        if path is not None:
            rev = self._edges.get((path[0], path[1]))
            rev_stack = _fmt_stack(rev["stack_to"]) if rev \
                else "<unrecorded>"
            cyc = " -> ".join([a, b] + path[1:])
            self._report(
                "lock-order-cycle", ("lock-order-cycle",) + tuple(
                    sorted((a, b))),
                f"lock acquisition cycle {cyc}: thread "
                f"{threading.current_thread().name!r} acquires {b!r} while "
                f"holding {a!r}, but the reverse order is also recorded — "
                "a schedule interleaving the two deadlocks",
                [f"--- this acquisition ({a} held, acquiring {b}):\n"
                 f"{_fmt_stack(stack_to)}",
                 f"--- reverse-order acquisition ({path[0]} held, "
                 f"acquiring {path[1]}, "
                 f"thread {rev['thread'] if rev else '?'}):\n{rev_stack}"])

    def _find_path(self, src: str, dst: str) -> Optional[list[str]]:
        """DFS over edge names: a path src ~> dst (callers hold
        _state_lock). Returns the node list, or None."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for (x, y) in self._edges:
                if x == node and y not in seen:
                    seen.add(y)
                    stack.append((y, path + [y]))
        return None

    def _check_hierarchy(self, held: list, to: _SanLock,
                         stack_to) -> None:
        lvl_to = self.hierarchy.get(to.name, {}).get("level")
        if lvl_to is None:
            return  # unregistered-in-table lock: cycle detection only
        worst = None
        for h in held:
            lvl = self.hierarchy.get(h.lock.name, {}).get("level")
            if lvl is not None and lvl > lvl_to and (
                    worst is None or lvl > worst[0]):
                worst = (lvl, h)
        if worst is None:
            return
        lvl, h = worst
        self._report(
            "hierarchy", ("hierarchy", h.lock.name, to.name),
            f"hierarchy violation: acquiring {to.name!r} (level {lvl_to}) "
            f"while holding {h.lock.name!r} (level {lvl}) — the "
            "lock_hierarchy.json order says the opposite nesting; thread "
            f"{threading.current_thread().name!r}",
            [f"--- holding {h.lock.name}:\n{_fmt_stack(h.stack)}",
             f"--- acquiring {to.name}:\n{_fmt_stack(stack_to)}"])

    # -- device-sync sanitizer ------------------------------------------------

    def check_fetch(self, point: str) -> None:
        """Assert no held registered lock forbids a device->host fetch.
        Called from the patched fetch points with a device value in hand."""
        with self._state_lock:
            self.fetch_checks += 1
        held = self._held()
        # innermost-first: when shard AND index locks are both held the
        # violation keys on the index lock — the most specific owner, and
        # the same key whether the call path entered through the shard or
        # hit the index directly (stable baseline keys)
        for h in reversed(held):
            if self.hierarchy.get(h.lock.name, {}).get("no_fetch_under"):
                site = _site_of(traceback.extract_stack())
                self._report(
                    "sync-under-lock",
                    ("sync-under-lock", h.lock.name, site),
                    f"device->host fetch ({point}) at {site} while holding "
                    f"{h.lock.name!r} — the snapshot plane's contract is "
                    "dispatch under the lock, fetch OUTSIDE it "
                    "(docs/concurrency.md); a helper hid this sync from "
                    "the lexical JGL008 check",
                    [f"--- fetch under {h.lock.name}:\n" + "".join(
                        traceback.format_stack(limit=20)[:-2]),
                     f"--- lock acquired at:\n{_fmt_stack(h.stack)}"])
                return

    # -- thread-leak sanitizer ------------------------------------------------

    @staticmethod
    def thread_snapshot() -> set:
        # Thread OBJECTS, not idents: the OS reuses pthread ids, so a
        # thread that exits mid-test can donate its ident to a freshly
        # leaked one and mask the leak nondeterministically
        return set(threading.enumerate())

    def leaked_threads(self, before: set, grace_s: float = 2.0) -> list:
        """Threads alive now, absent from ``before``, that the leak policy
        flags: any non-daemon thread, or a daemon thread of a watched
        module-global serving plane. Waits up to ``grace_s`` for
        stragglers whose stop was requested but not joined."""
        def suspects() -> list:
            out = []
            for t in threading.enumerate():
                if t in before or not t.is_alive() \
                        or t is threading.current_thread():
                    continue
                if not t.daemon or t.name.startswith(
                        WATCHED_THREAD_PREFIXES):
                    out.append(t)
            return out

        deadline = time.monotonic() + grace_s
        leaked = suspects()
        while leaked and time.monotonic() < deadline:
            for t in leaked:
                t.join(timeout=max(deadline - time.monotonic(), 0.01))
            leaked = suspects()
        for t in leaked:
            # per-instance key (the ident suffix): two tests each leaking
            # a same-named worker are two findings, not one deduped one —
            # a baseline entry may still waive by the ("thread-leak",
            # name) prefix
            self._report(
                "thread-leak", ("thread-leak", t.name, str(t.ident)),
                f"thread {t.name!r} (daemon={t.daemon}) leaked past its "
                "test — a tick/audit/flush thread that outlives its App "
                "shutdown/unconfigure works against freed state until an "
                "unrelated test flakes; use the configure/unconfigure "
                "fixtures (App.shutdown) instead of ad-hoc teardown", [])
        return leaked

    # -- violation store ------------------------------------------------------

    def _report(self, kind: str, key: tuple, message: str,
                stacks: list[str]) -> None:
        with self._state_lock:
            v = self._violations.get(key)
            if v is not None:
                v.count += 1
                return
            v = Violation(kind, key, message, stacks)
            for e in self._baseline:
                ek = tuple(e.get("key", ()))
                # an entry key may be a PREFIX of the violation key: a
                # thread-leak entry waives by name without the per-leak
                # ident suffix
                if e.get("kind") == kind and ek and key[:len(ek)] == ek:
                    v.baselined = True
                    v.justification = e.get(
                        "justification", "TODO: justify or fix")
                    break
            self._violations[key] = v
            self._order.append(v)

    def violations(self, baselined: bool = False) -> list[Violation]:
        with self._state_lock:
            return [v for v in self._order if baselined or not v.baselined]

    def mark(self) -> int:
        """Position in the violation stream; pair with ``since``."""
        with self._state_lock:
            return len(self._order)

    def since(self, mark: int) -> list[Violation]:
        """Unbaselined violations first seen after ``mark`` (repeat
        occurrences of an already-reported key do not re-fire)."""
        with self._state_lock:
            return [v for v in self._order[mark:] if not v.baselined]

    def report(self) -> dict:
        with self._state_lock:
            return {
                "enabled": sorted(self.enabled),
                "locks_registered": dict(self.locks_registered),
                "order_edges": [list(k) for k in sorted(self._edges)],
                "fetch_checks": self.fetch_checks,
                "violations": [v.as_dict() for v in self._order],
            }


def _site_of(frames) -> str:
    """The innermost weaviate_tpu frame below the sanitizer itself — the
    function a violation is attributed to (and baselined by). Falls back
    to the innermost non-library frame (a test's seeded helper) so a
    violation outside the package still names its culprit."""
    fallback = "<unknown>"
    for fr in reversed(frames):
        fn = fr.filename.replace(os.sep, "/")
        if fn.endswith("testing/sanitizers.py"):
            continue
        if "weaviate_tpu" in fn:
            return fr.name
        if fallback == "<unknown>" and "site-packages" not in fn \
                and "/lib/python" not in fn:
            fallback = fr.name
    return fallback


# -- fetch-point patching -----------------------------------------------------

_patched: Optional[dict] = None  # original callables while patched
# set while inside the named _fetch_packed point: its internal np.asarray
# must not report a SECOND violation keyed on the '_fetch_packed' frame —
# one fetch, one violation, keyed on the CALLER's site (stable baseline)
_in_named_fetch = threading.local()


def _install_sync_patches() -> None:
    """Patch the repo's device->host fetch points to route through
    ``check_fetch``. Each wrapper reads the LIVE module global (the
    faults.fire idiom), so a cleared sanitizer costs one comparison even
    while the patches linger between configure cycles."""
    global _patched
    if _patched is not None:
        return
    import jax
    import numpy as np

    from weaviate_tpu.index import tpu as tpu_mod

    orig_asarray = np.asarray
    orig_burr = jax.block_until_ready
    orig_fetch = tpu_mod._fetch_packed
    jax_array = jax.Array

    def asarray(*args, **kw):
        san = _sanitizer
        if san is not None and DEVICE_SYNC in san.enabled and args \
                and isinstance(args[0], jax_array) \
                and not getattr(_in_named_fetch, "active", False):
            san.check_fetch("np.asarray")
        return orig_asarray(*args, **kw)

    def block_until_ready(x):
        san = _sanitizer
        if san is not None and DEVICE_SYNC in san.enabled \
                and not getattr(_in_named_fetch, "active", False):
            san.check_fetch("jax.block_until_ready")
        return orig_burr(x)

    def fetch_packed(packed_dev, shape=None):
        # _fetch_packed's own np.asarray is also patched; the named point
        # checks ONCE (keyed on the caller's site) and suppresses the
        # inner patched points for the duration, so one fetch is one
        # violation a single baseline entry can waive
        san = _sanitizer
        if san is not None and DEVICE_SYNC in san.enabled:
            san.check_fetch("index.tpu._fetch_packed")
        _in_named_fetch.active = True
        try:
            return orig_fetch(packed_dev, shape)
        finally:
            _in_named_fetch.active = False

    np.asarray = asarray
    jax.block_until_ready = block_until_ready
    tpu_mod._fetch_packed = fetch_packed
    _patched = {"asarray": orig_asarray, "burr": orig_burr,
                "fetch": orig_fetch}


def _remove_sync_patches() -> None:
    global _patched
    if _patched is None:
        return
    import jax
    import numpy as np

    from weaviate_tpu.index import tpu as tpu_mod

    np.asarray = _patched["asarray"]
    jax.block_until_ready = _patched["burr"]
    tpu_mod._fetch_packed = _patched["fetch"]
    _patched = None


# -- module state + the zero-hop entry points ---------------------------------

_sanitizer: Optional[GraftSan] = None


def configure(san: Optional[GraftSan]) -> Optional[GraftSan]:
    """Install (or clear, with None) the process-wide sanitizer."""
    global _sanitizer
    _sanitizer = san
    if san is not None and DEVICE_SYNC in san.enabled:
        _install_sync_patches()
    return san


def unconfigure(san: GraftSan) -> None:
    """Clear only if still ``san`` (the still-ours discipline every other
    module-global plane honors)."""
    global _sanitizer
    if _sanitizer is san:
        _sanitizer = None
        _remove_sync_patches()


def get_sanitizer() -> Optional[GraftSan]:
    return _sanitizer


def register_lock(lock, name: str):
    """The construction-time shim the serving modules call: wrap ``lock``
    in the order-witnessing proxy when the sanitizer is up, return it
    unchanged otherwise — one comparison, nothing constructed."""
    san = _sanitizer
    if san is None:
        return lock
    return san.wrap_lock(lock, name)
