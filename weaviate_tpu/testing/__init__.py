"""In-process test/chaos instrumentation that ships WITH the package (not
under tests/): the fault-injection harness is reachable from a deployed
binary via config (``FAULT_INJECTION``), so failure journeys reproduce in
any environment, not just the unit-test tree. Import submodules directly
(``from weaviate_tpu.testing import faults``)."""
